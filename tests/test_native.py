"""Native C++ data-path kernels vs their numpy fallbacks."""

import numpy as np
import pytest
from PIL import Image

from dinov3_tpu import native
from dinov3_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD

requires_native = pytest.mark.skipif(
    not native.native_available(), reason="no C++ toolchain"
)


def _u8(h=33, w=47, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (h, w, 3), dtype=np.uint8
    )


@requires_native
def test_normalize_matches_numpy():
    arr = _u8()
    got = native.normalize_image(arr, IMAGENET_MEAN, IMAGENET_STD)
    mean = np.asarray(IMAGENET_MEAN, np.float32)
    std = np.asarray(IMAGENET_STD, np.float32)
    want = (arr.astype(np.float32) / 255.0 - mean) / std
    assert got.shape == want.shape and got.dtype == np.float32
    assert np.allclose(got, want, atol=1e-5)


@requires_native
def test_normalize_hflip():
    arr = _u8()
    got = native.normalize_image(arr, IMAGENET_MEAN, IMAGENET_STD, hflip=True)
    want = native.normalize_image(
        arr[:, ::-1], IMAGENET_MEAN, IMAGENET_STD
    )
    assert np.allclose(got, want, atol=1e-6)


@requires_native
def test_stack_crops_matches_numpy():
    rng = np.random.default_rng(0)
    items = [rng.standard_normal((8, 8, 3)).astype(np.float32)
             for _ in range(6)]
    got = native.stack_crops(items)
    assert np.array_equal(got, np.stack(items))
    # unsuitable inputs decline gracefully
    assert native.stack_crops([]) is None
    assert native.stack_crops(
        [items[0], items[1][:4]]  # shape mismatch
    ) is None


def test_to_normalized_array_uses_same_semantics_either_path(monkeypatch):
    from dinov3_tpu.data.transforms import to_normalized_array

    img = Image.fromarray(_u8(16, 16))
    with_native = to_normalized_array(img)
    monkeypatch.setenv("DINOV3_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", False)
    without = to_normalized_array(img)
    assert np.allclose(with_native, without, atol=1e-5)


def test_native_color_jitter_matches_numpy():
    import numpy as np
    import pytest

    from dinov3_tpu.data.transforms import (
        adjust_brightness,
        adjust_contrast,
        adjust_hue,
        adjust_saturation,
    )
    from dinov3_tpu.native import color_jitter, native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    arr = rng.uniform(0, 255, (64, 48, 3)).astype(np.float32)
    order = [3, 0, 2, 1]
    b, c, s, h = 1.3, 0.8, 1.1, 0.21

    ref = arr.copy()
    for op in order:
        if op == 0:
            ref = adjust_brightness(ref, b)
        elif op == 1:
            ref = adjust_contrast(ref, c)
        elif op == 2:
            ref = adjust_saturation(ref, s)
        elif op == 3:
            ref = adjust_hue(ref, h)

    got = color_jitter(arr.copy(), order, b, c, s, h)
    assert got is not None
    # identical math modulo float32-vs-float64 intermediates; after the
    # final uint8 quantization any residual differs by at most 1 level
    diff = np.abs(got.astype(np.int32).astype(np.float32) - ref)
    assert np.percentile(diff, 99.9) <= 1.5, diff.max()


def test_native_color_jitter_skips_none_factors():
    import numpy as np
    import pytest

    from dinov3_tpu.native import color_jitter, native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    arr = np.full((8, 8, 3), 100.0, np.float32)
    got = color_jitter(arr.copy(), [0, 1, 2, 3], None, None, None, None)
    assert got is not None
    np.testing.assert_array_equal(got, arr)
