"""Unified parallelism engine (zero3 x bucketed collectives x
microbatched gradient accumulation) vs its per-leaf / monolithic
oracles.

The unified arm (``parallel.zero3`` + ``optim.bucketed_collectives`` on
an fsdp>1 mesh, train/setup.py) coalesces the NON-block zero3 subtree
gathers of the forward into hierarchy-aware flat buckets
(train/fused_update.py ``make_zero3_bucket_plan`` /
``gather_zero3_bucketed``): members grouped by (top-level submodel,
dtype, zero3 shard_dim), packed with NO padding (every member's sharded
dim divides the data-axis product by construction), gathered inter-tier
first then intra (scopes ``bucket_ag_inter`` / ``bucket_ag_intra``)
with the transposed grad reduce-scatter staged the other way
(``bucket_rs_intra`` / ``bucket_rs_inter``). The per-leaf zero3 gather
stays in the tree as the bitwise oracle behind
``bucketed_collectives=false``; the in-scan block stream is untouched
by design. ``optim.accum_steps`` scans the fwd/bwd over equal
microbatches with the gathers HOISTED as scan constants, so ONE
bucketed grad-RS per bucket fires per optimizer step regardless of
accum_steps.

These tests pin:

- the gather-plan layout (grouping key, zero-padding-free packing,
  streamed/perleaf classification, byte-target splitting) and the
  member pack/unpack round-trip;
- the microbatch split's crop-major regroup semantics and its
  guardrails (trace-time raise + ``warn_accum_batch_tiling``);
- setup wiring: unified auto-on for zero3 fsdp meshes, per-leaf oracle
  behind ``=false``, and the LIFTED raise (bucketed=true now composes
  with zero3 instead of raising);
- unified vs per-leaf zero3 dryrun equivalence on a dp x fsdp mesh
  (same-state seeding, PR-7 tolerances);
- accum_steps in {1,2,4} loss trajectories vs the monolithic oracle
  (fp32 + batch-decoupled losses: the microbatch means are the batch
  means up to summation order — the sliced microbatch is pinned back
  onto the canonical batch layout inside the scan, without which the
  partitioner picks a DIFFERENT layout than the monolithic arm and the
  arms diverge ~1e-2);
- the compiled step's collective census: coalesced bucket gathers
  attributed on BOTH mesh tiers, zero unattributed collectives, scoped
  grad-RS present, and bucket collective counts INVARIANT in
  accum_steps;
- the explicit schedule twin (``make_zero3_gather_schedule``): forward
  bitwise vs the per-leaf oracle and the host values, per-tier scope
  ops exactly one per bucket, grads matching at float tolerance;
- the hierarchical option of the bucketed stream scan (bitwise vs the
  flat gather, staged scopes present);
- cross-arm checkpoints (unified <-> per-leaf zero3 bitwise + resume
  determinism; PR-5 flat-arm checkpoint restoring into the unified
  arm);
- the committed COST_UNIFIED_r18.json acceptance numbers.
"""

import json
import os

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
from dinov3_tpu.parallel.sharding import hierarchy_axes, zero3_leaf_spec
from dinov3_tpu.train.fused_update import (
    Zero3GatherPlan,
    _zero3_member_rows,
    _zero3_member_unrows,
    make_zero3_bucket_plan,
    make_zero3_gather_schedule,
    zero3_streamed_path,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "telemetry.async_metrics=false",
]

# batch-decoupled loss config for the accum trajectory pins: sinkhorn
# (batch-normalized), koleo (batch kNN) and drop-path (per-microbatch
# draws) genuinely couple the loss to the batch partition, so the
# microbatch means only equal the monolithic means without them
NEUTRAL = [
    "train.centering=softmax_center",
    "dino.koleo_loss_weight=0.0",
    "student.drop_path_rate=0.0",
    "compute_precision.compute_dtype=fp32",
]


def _setup(extra, batch_size, devices):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=devices), batch


def _use(s):
    """Re-pin the ambient current-mesh to this setup's mesh: tests in
    this file build setups on several mesh shapes, and tracing a
    setup's step_fn under another setup's mesh context silently
    resolves the layout constraints against the wrong mesh."""
    from dinov3_tpu.parallel.context import set_current_mesh

    set_current_mesh(s.mesh)
    return s


def _flat_params(tree):
    return jtu.tree_flatten_with_path(tree)[0]


def assert_trees_bitwise(a, b, what, limit=None):
    fa, fb = _flat_params(a), _flat_params(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in (zip(fa, fb) if limit is None
                              else zip(fa[:limit], fb[:limit])):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: {jtu.keystr(pa)} differs")


def _dp_fsdp_mesh(devices):
    return build_mesh(MeshSpec(data=2, fsdp=4), devices=devices)


def _zero3_put(tree, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    def leaf(x):
        spec = zero3_leaf_spec(x.shape, (None,) * x.ndim, mesh)
        return jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, spec if spec else P()))
    return jax.tree.map(leaf, tree)


def _toy_tree(with_blocks=False):
    rng = np.random.default_rng(0)
    tree = {
        "backbone": {
            "patch_embed": {
                "kernel": rng.normal(size=(4, 4, 3, 16)).astype(np.float32),
                "bias": rng.normal(size=(16,)).astype(np.float32)},
            "norm": {"scale": rng.normal(size=(16,)).astype(np.float32)},
            "cls_token": rng.normal(size=(1, 1, 16)).astype(np.float32),
            # no dim divides dp=8 -> perleaf
            "odd": rng.normal(size=(3, 5)).astype(np.float32),
        },
        "dino_head": {
            "mlp1": {"kernel": rng.normal(size=(16, 64)).astype(np.float32),
                     "bias": rng.normal(size=(64,)).astype(np.float32)},
            "last": {"kernel": rng.normal(size=(64, 32)).astype(np.float32)},
        },
    }
    if with_blocks:
        tree["backbone"]["blocks"] = {
            "attn": {"kernel": rng.normal(size=(4, 16, 16)
                                          ).astype(np.float32)}}
    return tree


# ---------------- gather-plan layout ----------------

def test_zero3_streamed_path_rule():
    class K:
        def __init__(self, key):
            self.key = key

    assert zero3_streamed_path((K("backbone"), K("blocks"), K("kernel")))
    assert zero3_streamed_path((K("blocks_3"), K("kernel")))
    assert zero3_streamed_path((K("pipeline"), K("w")))
    assert not zero3_streamed_path((K("backbone"), K("patch_embed")))
    assert not zero3_streamed_path((K("dino_head"), K("blocksmith")))


def test_bucket_plan_grouping_and_no_padding(eight_devices):
    mesh = _dp_fsdp_mesh(eight_devices)
    plan = make_zero3_bucket_plan(_toy_tree(), mesh)
    assert isinstance(plan, Zero3GatherPlan)
    assert plan.n_inter == 2 and plan.n_intra == 4 and plan.dp == 8
    assert not plan.streamed
    # the (3,5) leaf has no dp-dividing dim -> perleaf oracle gather
    assert len(plan.perleaf) == 1
    for b in plan.buckets:
        # one (submodel, dtype, shard_dim) per bucket
        assert all(m.shard_dim == b.shard_dim for m in b.members)
        assert b.name.endswith(b.group)
        # zero-padding-free packing: cols * dp == size member for
        # member, offsets contiguous
        off = 0
        for m in b.members:
            assert m.cols * plan.dp == m.size
            assert m.offset == off
            off += m.cols
        assert b.cols == off
    # every non-streamed, non-perleaf leaf is in exactly one bucket
    covered = sorted(m.index for b in plan.buckets for m in b.members)
    assert len(covered) == len(set(covered))
    assert len(covered) + len(plan.perleaf) == plan.n_leaves
    # submodels never share a bucket
    assert {b.group for b in plan.buckets} <= {"backbone", "dino_head"}


def test_bucket_plan_streamed_exclusion(eight_devices):
    mesh = _dp_fsdp_mesh(eight_devices)
    plan = make_zero3_bucket_plan(_toy_tree(with_blocks=True), mesh)
    assert len(plan.streamed) == 1
    bucketed = {m.index for b in plan.buckets for m in b.members}
    assert not bucketed & set(plan.streamed)
    for b in plan.buckets:
        for m in b.members:
            assert "blocks" not in m.path


def test_bucket_plan_byte_target_split(eight_devices):
    mesh = _dp_fsdp_mesh(eight_devices)
    small = make_zero3_bucket_plan(_toy_tree(), mesh, target_bytes=2 ** 10)
    big = make_zero3_bucket_plan(_toy_tree(), mesh, target_bytes=2 ** 30)
    assert len(small.buckets) > len(big.buckets)
    # the byte target caps buckets except single oversized members
    for b in small.buckets:
        nbytes = b.cols * small.dp * jnp.dtype(b.dtype).itemsize
        assert nbytes <= 2 ** 10 or len(b.members) == 1
    assert small.stats()  # accounting rows build


def test_member_rows_unrows_roundtrip(eight_devices):
    mesh = _dp_fsdp_mesh(eight_devices)
    plan = make_zero3_bucket_plan(_toy_tree(), mesh)
    leaves = [leaf for _, leaf in
              jtu.tree_flatten_with_path(_toy_tree())[0]]
    for b in plan.buckets:
        for m in b.members:
            leaf = jnp.asarray(leaves[m.index])
            rows = _zero3_member_rows(
                leaf, m, plan.n_inter, plan.n_intra)
            assert rows.shape == (plan.n_inter, plan.n_intra, m.cols)
            back = _zero3_member_unrows(rows, m)
            assert back.shape == m.shape
            np.testing.assert_array_equal(np.asarray(back),
                                          np.asarray(leaf))


def test_hierarchy_axes_tiers(eight_devices):
    mesh = _dp_fsdp_mesh(eight_devices)
    inter, intra = hierarchy_axes(mesh)
    assert inter == ("data",) and intra == ("fsdp",)
    dp_only = build_mesh(MeshSpec(data=8), devices=eight_devices)
    inter, intra = hierarchy_axes(dp_only)
    assert inter == () and intra == ("data",)


# ---------------- microbatch split ----------------

def test_split_microbatches_crop_major_regroup():
    from dinov3_tpu.train.train_step import split_microbatches

    B, accum = 8, 4
    # k=2 crop-major leaf: value encodes (crop, image)
    g = jnp.arange(2 * B).reshape(2 * B, 1)
    l = jnp.arange(3 * B).reshape(3 * B, 1)  # k=3
    out = split_microbatches({"global_crops": g, "local": l,
                              "s": jnp.float32(3.0)}, accum)
    m = B // accum
    for leaf, k in (("global_crops", 2), ("local", 3)):
        arr = out[leaf]
        assert arr.shape[0] == accum and arr.shape[1] == k * m
        for a in range(accum):
            for c in range(k):
                for i in range(m):
                    # microbatch a holds ALL k crops of image subset a,
                    # itself crop-major
                    assert int(arr[a, c * m + i, 0]) == c * B + a * m + i
    assert out["s"].ndim == 0  # scalars broadcast unchanged
    same = split_microbatches({"global_crops": g}, 1)
    assert same["global_crops"] is g  # accum=1 is a pass-through


def test_split_microbatches_raises_on_bad_tiling():
    from dinov3_tpu.train.train_step import split_microbatches

    g = jnp.zeros((2 * 6, 1))
    with pytest.raises(ValueError, match="optim.accum_steps"):
        split_microbatches({"global_crops": g}, 4)  # 6 % 4 != 0


def test_warn_accum_batch_tiling_guardrail():
    from dinov3_tpu.configs.config import warn_accum_batch_tiling

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + ["optim.accum_steps=3"])
    with pytest.warns(UserWarning, match="optim.accum_steps axis"):
        msgs = warn_accum_batch_tiling(cfg, per_chip_batch=2)
    assert msgs and "does not divide" in msgs[0]
    # dividing accum on a clean microbatch: silent
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, SMOL + ["optim.accum_steps=2"])
    assert warn_accum_batch_tiling(cfg2, per_chip_batch=16) == []


# ---------------- setup wiring ----------------

@pytest.fixture(scope="module")
def arms_unified(eight_devices):
    """Unified arm + its per-leaf zero3 oracle on the dp x fsdp mesh,
    fp32 compute (PR-7 dryrun convention), with the put batch."""
    from dinov3_tpu.train import put_batch

    common = ["parallel.data=-1", "parallel.fsdp=2",
              "parallel.zero3=auto", "optim.sharded_update=false",
              "compute_precision.compute_dtype=fp32"]
    s_u, batch = _setup(common, 16, eight_devices)
    s_o, _ = _setup(common + ["optim.bucketed_collectives=false"], 16,
                    eight_devices)
    d = put_batch(batch, s_u.batch_shardings)
    return s_u, s_o, d


def test_setup_unified_wiring(arms_unified):
    s_u, s_o, _ = arms_unified
    # auto composes zero3 + buckets on the fsdp mesh; =false keeps the
    # per-leaf oracle on the same zero3 layout
    assert s_u.zero3 and s_u.zero3_buckets
    assert s_o.zero3 and not s_o.zero3_buckets
    plan = s_u.zero3_bucket_plan
    assert plan is not None and len(plan.buckets) >= 1
    assert plan.streamed  # the block stack stays with the in-scan stream
    assert plan.dp == 8


def test_setup_explicit_bucketed_composes_with_zero3(eight_devices):
    """The lifted raise: bucketed_collectives=true + zero3 no longer
    conflicts — it selects the unified arm even with the fused update
    disabled."""
    s, _ = _setup(["parallel.data=-1", "parallel.fsdp=2",
                   "parallel.zero3=auto", "optim.sharded_update=false",
                   "optim.bucketed_collectives=true"], 16, eight_devices)
    assert s.zero3 and s.zero3_buckets


def test_setup_bucketed_raise_names_unified_arm(eight_devices):
    """On NON-zero3 meshes the explicit-bucketed requirements still
    raise, and the error text points at the unified arm as the
    exception."""
    with pytest.raises(ValueError, match="unified zero3 gather-bucket"):
        _setup(["parallel.data=-1", "parallel.zero3=false",
                "optim.fused_update=false",
                "optim.bucketed_collectives=true"], 16, jax.devices())


# ---------------- unified vs per-leaf dryrun equivalence ----------------

def test_dryrun_unified_vs_perleaf_zero3(arms_unified):
    """Both arms share the zero3 state layout, so they start from the
    SAME state (pure re-placement); two steps must match at the PR-7
    dp x fsdp tolerances — only reduction associativity separates the
    bucketed staged gathers from the per-leaf ones in fp32."""
    s_u, s_o, d = arms_unified
    results = {}
    for name, setup in (("unified", s_u), ("perleaf", s_o)):
        _use(setup)
        # step from a COPY: step_fn donates its state input, and the
        # two arms share the zero3 layout (device_put would alias)
        state = jax.tree.map(jnp.copy, s_u.state)
        for i in range(2):
            state, m = setup.step_fn(state, d, setup.scalars(i),
                                     jax.random.key(0))
        results[name] = (state, float(m["total_loss"]))
    assert results["unified"][1] == pytest.approx(results["perleaf"][1],
                                                  rel=1e-5)
    for (pa, la), (_, lb) in zip(
        _flat_params(results["unified"][0].params)[:48],
        _flat_params(results["perleaf"][0].params)[:48],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=5e-6, atol=1e-6,
            err_msg=f"unified vs perleaf params {jtu.keystr(pa)}")


# ---------------- collective census of the compiled step ----------------

def test_unified_step_census_both_tiers(arms_unified):
    """The compiled unified step gathers on BOTH mesh tiers under the
    bucket scopes with zero unattributed collectives, and its grad
    reduce-scatter carries the staged bucket_rs scopes; the per-leaf
    oracle has none of the bucket scopes."""
    from dinov3_tpu.utils import hlo_collective_census

    s_u, s_o, d = arms_unified
    _use(s_u)
    text = s_u.step_fn.lower(
        s_u.state, d, s_u.scalars(0), jax.random.key(0)
    ).compile().as_text()
    cen = hlo_collective_census(text)
    assert cen["unattributed"] == 0
    ag_inter = cen["by_scope"].get("bucket_ag_inter", {"ops": 0})["ops"]
    ag_intra = cen["by_scope"].get("bucket_ag_intra", {"ops": 0})["ops"]
    assert ag_inter > 0 and ag_intra > 0
    # the staged grad-RS scope reaches the compiled text (this backend
    # lowers reduce-scatter as all-reduce+slice and fuses the intra
    # stage away entirely — the exact per-tier RS pin lives in the
    # explicit schedule-twin test below)
    assert "bucket_rs_inter" in text

    _use(s_o)
    text_o = s_o.step_fn.lower(
        s_o.state, d, s_o.scalars(0), jax.random.key(0)
    ).compile().as_text()
    cen_o = hlo_collective_census(text_o)
    assert not any(k.startswith("bucket_") for k in cen_o["by_scope"])
    assert cen_o["by_scope"].get("zero3_gather", {"ops": 0})["ops"] > 0


# ---------------- microbatched accumulation ----------------

@pytest.fixture(scope="module")
def accum_arms(eight_devices):
    """Unified-arm setups at accum_steps 1/2/4 on the dp x fsdp mesh
    with the batch-decoupled fp32 config, each run 3 steps."""
    from dinov3_tpu.train import put_batch

    common = ["parallel.data=-1", "parallel.fsdp=2",
              "parallel.zero3=auto", "optim.sharded_update=false"]
    out = {}
    d = None
    for accum in (1, 2, 4):
        s, batch = _setup(
            common + NEUTRAL + [f"optim.accum_steps={accum}"], 16,
            eight_devices)
        assert s.accum_steps == accum and s.zero3_buckets
        if d is None:
            d = put_batch(batch, s.batch_shardings)
        # step from a copy: step_fn donates, and the census test below
        # still needs s.state alive to lower against
        state, losses = jax.tree.map(jnp.copy, s.state), []
        for i in range(3):
            state, m = s.step_fn(state, d, s.scalars(i),
                                 jax.random.key(0))
            losses.append(float(m["total_loss"]))
        out[accum] = (s, losses, state)
    return out, d


def test_accum_loss_trajectory_vs_monolithic(accum_arms):
    """accum_steps in {2,4} track the monolithic (accum=1) oracle: the
    losses are batch-decoupled, so the microbatch means equal the batch
    means up to fp32 summation order — plus the (intended) equal-weight
    ibot-center EMA mean, which enters from step 2. The sliced
    microbatch is pinned onto the canonical batch layout inside the
    scan (train_step.py); without that constraint the partitioner picks
    a different layout and the arms drift ~1e-2."""
    arms, _ = accum_arms
    l1 = np.array(arms[1][1])
    assert np.all(np.isfinite(l1))
    for a in (2, 4):
        la = np.array(arms[a][1])
        assert np.all(np.isfinite(la))
        np.testing.assert_allclose(la, l1, rtol=5e-4,
                                   err_msg=f"accum={a} trajectory")
        # params stay in lockstep (adam normalization amplifies the
        # summation-order noise, so this is a drift bound, not bitwise)
        for (pa, x), (_, y) in zip(
            _flat_params(arms[1][2].params["student"])[:48],
            _flat_params(arms[a][2].params["student"])[:48],
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), atol=5e-3,
                err_msg=f"accum={a} params {jtu.keystr(pa)}")


def test_accum_invariant_bucket_collectives(accum_arms):
    """ONE gather per bucket and one staged grad-RS per bucket per
    OPTIMIZER STEP, regardless of accum_steps: the gathers are hoisted
    out of the microbatch scan as scan constants, so the bucket scope
    op counts of the compiled accum=2 step equal the accum=1 step's."""
    from dinov3_tpu.utils import hlo_collective_census

    arms, d = accum_arms
    counts = {}
    for a in (1, 2):
        s = _use(arms[a][0])
        text = s.step_fn.lower(
            s.state, d, s.scalars(0), jax.random.key(0)
        ).compile().as_text()
        cen = hlo_collective_census(text)
        assert cen["unattributed"] == 0
        # censused COLLECTIVE op counts only: raw scope-string line
        # counts also hit fusion metadata, which the microbatch scan
        # duplicates
        counts[a] = {
            "ag_inter": cen["by_scope"].get(
                "bucket_ag_inter", {"ops": 0})["ops"],
            "ag_intra": cen["by_scope"].get(
                "bucket_ag_intra", {"ops": 0})["ops"],
        }
        assert counts[a]["ag_inter"] > 0 and counts[a]["ag_intra"] > 0
        assert text.count("bucket_rs_inter") > 0
    assert counts[1] == counts[2]


# ---------------- explicit schedule twin ----------------

def test_gather_schedule_twin_numerics_and_census(eight_devices):
    """The explicit staged-bucket schedule: forward bitwise == the
    per-leaf oracle == the host values; per-tier scope ops exactly one
    per bucket; zero unattributed; grads match the oracle at float
    tolerance (the RS transpose only reorders the reduction)."""
    from dinov3_tpu.utils import hlo_collective_census

    mesh = _dp_fsdp_mesh(eight_devices)
    tree_np = _toy_tree()
    tree = _zero3_put(tree_np, mesh)
    plan = make_zero3_bucket_plan(tree, mesh, target_bytes=2 ** 10)
    assert len(plan.buckets) >= 2

    g_b = make_zero3_gather_schedule(plan, mesh, bucketed=True)
    g_o = make_zero3_gather_schedule(plan, mesh, bucketed=False)
    out_b = jax.jit(g_b)(tree)
    out_o = jax.jit(g_o)(tree)
    ref = jax.tree.map(jnp.asarray, tree_np)
    assert_trees_bitwise(out_b, out_o, "bucketed vs per-leaf forward")
    assert_trees_bitwise(out_b, ref, "bucketed forward vs host values")

    def loss_of(g):
        def loss(t):
            # NONLINEAR consume: a linear sum lets XLA reassociate
            # sum(all_gather(x)) into local-sum + all-reduce and the
            # censused gathers vanish from the compiled program
            return sum(jnp.sum(jnp.sin(l.astype(jnp.float32)))
                       for l in jax.tree.leaves(g(t)))
        return loss

    gb = jax.jit(jax.grad(loss_of(g_b)))(tree)
    go = jax.jit(jax.grad(loss_of(g_o)))(tree)
    for a, b in zip(jax.tree.leaves(gb), jax.tree.leaves(go)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    nb = len(plan.buckets)
    cen = hlo_collective_census(
        jax.jit(jax.grad(loss_of(g_b))).lower(tree).compile().as_text())
    assert cen["unattributed"] == 0
    for scope in ("bucket_ag_inter", "bucket_ag_intra",
                  "bucket_rs_intra", "bucket_rs_inter"):
        assert cen["by_scope"].get(scope, {"ops": 0})["ops"] == nb, scope


def test_hierarchical_stream_scan_bitwise(eight_devices):
    """The bucketed stream scan's hierarchical option: the staged
    inter->intra gather + order-restoring swap is BITWISE the flat
    tiled gather, with both tier scopes attributed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import bucketed_stream_scan
    from dinov3_tpu.utils import hlo_collective_census

    mesh = _dp_fsdp_mesh(eight_devices)
    shards = jnp.arange(4 * 64, dtype=jnp.float32).reshape(4, 64) * 0.01
    x = jnp.ones((8, 16), jnp.bfloat16)
    sh = jax.device_put(
        shards, NamedSharding(mesh, P(None, ("data", "fsdp"))))
    xx = jax.device_put(x, NamedSharding(mesh, P("data")))

    y_flat = jax.jit(lambda s, v: bucketed_stream_scan(
        s, v, mesh=mesh))(sh, xx)
    y_hier = jax.jit(lambda s, v: bucketed_stream_scan(
        s, v, mesh=mesh, hierarchical=True))(sh, xx)
    np.testing.assert_array_equal(np.asarray(y_flat), np.asarray(y_hier))

    comp = jax.jit(lambda s, v: jnp.sum(bucketed_stream_scan(
        s, v, mesh=mesh, hierarchical=True).astype(jnp.float32))
    ).lower(sh, xx).compile()
    cen = hlo_collective_census(comp.as_text())
    assert cen["unattributed"] == 0
    assert cen["by_scope"].get("bucket_ag_inter", {"ops": 0})["ops"] > 0
    assert cen["by_scope"].get("bucket_ag_intra", {"ops": 0})["ops"] > 0


# ---------------- cross-arm checkpoints ----------------

def test_checkpoint_unified_perleaf_roundtrip(tmp_path, arms_unified):
    """unified -> per-leaf zero3 -> unified: identical state layouts,
    so the round trip is a pure re-placement — bitwise both ways, and
    the resumed unified run is deterministic against the uninterrupted
    one."""
    from dinov3_tpu.checkpoint import Checkpointer

    s_u, s_o, d = arms_unified
    _use(s_u)
    state1, _ = s_u.step_fn(jax.tree.map(jnp.copy, s_u.state), d,
                            s_u.scalars(0), jax.random.key(0))
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, state1)
    ck.wait_until_finished()

    o_state = ck.restore(s_o.state, 1)
    assert_trees_bitwise(state1.params, o_state.params,
                         "unified -> perleaf params")
    ck.save(2, o_state)
    ck.wait_until_finished()
    back = ck.restore(s_u.state, 2)
    assert_trees_bitwise(state1.opt_state, back.opt_state,
                         "round-trip opt state")

    # all cross-arm comparisons done; the steps below DONATE their
    # state inputs, so they come last
    _use(s_o)
    _, m_o = s_o.step_fn(o_state, d, s_o.scalars(1), jax.random.key(0))
    assert np.isfinite(float(m_o["total_loss"]))
    _use(s_u)
    st_a, m_a = s_u.step_fn(state1, d, s_u.scalars(1), jax.random.key(0))
    st_b, m_b = s_u.step_fn(back, d, s_u.scalars(1), jax.random.key(0))
    assert float(m_a["total_loss"]) == float(m_b["total_loss"])
    assert_trees_bitwise(st_a.params, st_b.params, "resume determinism",
                         limit=32)


def test_checkpoint_flat_arm_into_unified(tmp_path, eight_devices):
    """A dp-only PR-5 flat-sharded-update checkpoint restores into the
    unified zero3 arm (moments come back model-shaped through the
    flat->full adapt path) and the unified step runs from it."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch

    s_flat, batch = _setup(["parallel.zero3=false",
                            "optim.bucketed_collectives=false"], 16,
                           eight_devices)
    assert s_flat.sharded_update and not s_flat.zero3
    d_flat = put_batch(batch, s_flat.batch_shardings)
    state1, _ = s_flat.step_fn(s_flat.state, d_flat, s_flat.scalars(0),
                               jax.random.key(0))
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, state1)
    ck.wait_until_finished()

    s_u, batch_u = _setup(
        ["parallel.data=-1", "parallel.fsdp=2", "parallel.zero3=auto",
         "optim.sharded_update=false"], 16, eight_devices)
    assert s_u.zero3_buckets
    restored = ck.restore(s_u.state, 1)
    assert_trees_bitwise(state1.params, restored.params,
                         "flat -> unified params")
    d_u = put_batch(batch_u, s_u.batch_shardings)
    _, m = s_u.step_fn(restored, d_u, s_u.scalars(1), jax.random.key(0))
    assert np.isfinite(float(m["total_loss"]))


# ---------------- committed artifact acceptance ----------------

def test_cost_unified_artifact_acceptance():
    """COST_UNIFIED_r18.json (scripts/cost_unified.py, ViT-L on the
    2x4 data x fsdp mesh): the unified arm's committed collective-set
    numbers hold — per-leaf RS count equals the shardable leaf count,
    the unified arm pays one staged pair per bucket with fewer buckets
    than leaves, and the accum sweep is collective-count invariant with
    finite executed loss trajectories."""
    with open(os.path.join(REPO, "COST_UNIFIED_r18.json")) as f:
        j = json.load(f)
    assert j["mesh"] == {"data": 2, "fsdp": 4}
    gp = j["gather_phase"]
    n_shard = gp["n_shardable_leaves"]
    nb = gp["plan"]["n_buckets"]
    assert 1 <= nb < n_shard
    assert gp["plan"]["n_inter"] == 2 and gp["plan"]["n_intra"] == 4
    rs = j["reduce_scatter_ops"]
    assert rs["per_leaf"] == n_shard
    assert rs["unified"] == 2 * nb  # one intra + one inter stage/bucket
    assert rs["unified"] < rs["per_leaf"]
    ag = j["all_gather_ops"]
    assert ag["per_leaf"] == n_shard and ag["unified"] == 2 * nb
    sweep = j["accum_sweep"]
    assert [e["accum_steps"] for e in sweep] == [1, 2, 4]
    base = None
    for e in sweep:
        assert e["n_buckets"] == nb
        assert e["grad_rs_scope_lines"] > 0
        cen = e["collective_census"]
        assert cen["unattributed"] == 0
        tiers = {k: v["ops"] for k, v in cen["by_scope"].items()
                 if k.startswith("bucket_ag_")}
        assert tiers.get("bucket_ag_inter", 0) > 0
        assert tiers.get("bucket_ag_intra", 0) > 0
        if base is None:
            base = tiers
        assert tiers == base  # one gather per bucket per step
        assert all(np.isfinite(v) for v in e["loss_trajectory"])
