"""Async telemetry engine (dinov3_tpu/telemetry/): on-device metrics
ring, host phase-span tracer, memory accounting.

The async metrics path is the default (``telemetry.async_metrics``
auto=on); the per-step ``float(v)`` fetch stays as the oracle behind
=false. These tests pin:
- ring wraparound + the RingReader's exact-window replay (iteration
  stamps verified per slot; cursor drift and too-wide windows raise);
- oracle-vs-ring BITWISE metric equality over a multi-step dryrun on
  the 8-device mesh (same seeded program, per-step ``float(v)`` values
  vs flushed rows);
- the device-side finite-flag: consecutive non-finite ``total_loss``
  streak counts across steps AND across flush boundaries (the 3-strike
  abort's flush-granularity latency can delay the abort, never miss
  it);
- copy census of the exact compiled telemetry step: the ring write is
  attributed to the "telemetry" named-scope category
  (utils.classify_copy) and the ceiling is pinned a small delta over
  the oracle step — no copy-census regression, no new "large" class;
- span JSONL schema + heartbeat mtime advance, from both the unit
  tracer and a short CPU dryrun of train/train.py (the acceptance
  artifact: spans + heartbeat + memory records + exact recorded
  losses + --benchmark under async metrics);
- resume mid-ring determinism: a run killed mid flush-window resumes
  from the checkpoint and records the same per-iteration losses as the
  uninterrupted run;
- the --benchmark explicit fence (StepTimer) agreeing with the old
  free-ride-on-the-metrics-fetch timing on the oracle path, where both
  exist;
- the ``warn_telemetry_flush_period`` config guardrail;
- the blocking-fetch funnel (host_sync) and the memory instruments.
"""

import json
import math
import os
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.telemetry import (
    RingReader,
    SpanTracer,
    StepTimer,
    blocking_fetch,
    host_sync_stats,
    make_ring,
    per_device_state_bytes,
    sample_memory,
    telemetry_wished,
    write_row,
)
from test_fused_update import smol_cfg

TINY_TRAIN = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "data.backend=synthetic",
    "optim.epochs=1", "optim.warmup_epochs=0",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
]


def _setup(extra, batch_size=8, devices=None):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = smol_cfg(extra)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=devices), batch


# ---------------- ring unit behavior ----------------

def _mk(loss, aux=None):
    return {"total_loss": jnp.float32(loss),
            "aux": jnp.float32(loss * 2 if aux is None else aux)}


NAMES = ["aux", "total_loss"]  # sorted metric-name order


def test_ring_wraparound_and_reader_windows():
    """10 writes through a K=4 ring, flushed in full + partial windows:
    every row comes back exact, in iteration order, stamps verified."""
    K = 4
    ring = jax.device_put(make_ring(len(NAMES), K))
    step = jax.jit(
        lambda r, it, v: write_row(r, it, _mk(v), NAMES))
    reader = RingReader(NAMES, K)
    got_its, got_loss = [], []
    for it in range(10):
        ring = step(ring, jnp.int32(it), jnp.float32(it + 0.5))
        if it in (3, 7, 9):  # two full windows + one partial
            its, rows, streak = reader.flush(ring, it + 1)
            assert streak == 0
            got_its += its.tolist()
            got_loss += rows[:, NAMES.index("total_loss")].tolist()
            np.testing.assert_array_equal(
                rows[:, NAMES.index("aux")],
                2.0 * np.asarray(its, np.float32) + 1.0)
    assert got_its == list(range(10))
    np.testing.assert_array_equal(
        got_loss, np.arange(10, dtype=np.float32) + 0.5)
    assert reader.cursor == 10


def test_ring_reader_rejects_bad_windows():
    K = 4
    ring = jax.device_put(make_ring(len(NAMES), K))
    step = jax.jit(lambda r, it: write_row(r, it, _mk(1.0), NAMES))
    for it in range(6):
        ring = step(ring, jnp.int32(it))
    # window wider than the ring: a missed flush, structural
    with pytest.raises(RuntimeError, match="does not fit the ring"):
        RingReader(NAMES, K).flush(ring, 6)
    # cursor drift: slots 0,1 were overwritten by iterations 4,5
    with pytest.raises(RuntimeError, match="stamp mismatch"):
        RingReader(NAMES, K, start_iteration=0).flush(ring, 2)
    # the aligned reader is fine
    its, rows, _ = RingReader(NAMES, K, start_iteration=4).flush(ring, 6)
    assert its.tolist() == [4, 5]


def test_finite_flag_streak_counts_across_flushes():
    """The device-side non-finite streak: grows on consecutive
    non-finite total_loss, resets on finite, and counts ACROSS flush
    boundaries (flushing reads, never resets)."""
    K = 3
    ring = jax.device_put(make_ring(len(NAMES), K))
    step = jax.jit(
        lambda r, it, v: write_row(r, it, _mk(v, aux=0.0), NAMES))
    seq = [1.0, float("nan"), float("inf"), 1.0, float("nan"),
           float("nan")]
    want_streak = [0, 1, 2, 0, 1, 2]
    for it, (v, want) in enumerate(zip(seq, want_streak)):
        ring = step(ring, jnp.int32(it), jnp.float32(v))
        assert int(jax.device_get(ring.nonfinite_streak)) == want
    # a flush mid-streak surfaces the streak without resetting it...
    reader = RingReader(NAMES, K, start_iteration=3)
    its, rows, streak = reader.flush(ring, 6)  # window [3, 6)
    assert streak == 2
    assert np.isnan(rows[-1, NAMES.index("total_loss")])
    # ...and the device streak keeps counting across the flush boundary:
    # a third consecutive non-finite step crosses the 3-strike threshold
    # even though a flush intervened
    ring = step(ring, jnp.int32(6), jnp.float32(float("nan")))
    assert int(jax.device_get(ring.nonfinite_streak)) == 3


def test_ring_scalar_only_guard():
    ring = jax.device_put(make_ring(1, 2))
    with pytest.raises(ValueError, match="scalar metrics only"):
        jax.jit(lambda r: write_row(
            r, jnp.int32(0), {"total_loss": jnp.zeros((2,))},
            ["total_loss"]))(ring)


# ---------------- full-step: equality, census, wiring ----------------

def test_oracle_vs_ring_bitwise_metric_equality(eight_devices):
    """Same seeded program, 5 steps on the 8-device mesh: the flushed
    ring rows equal the oracle's per-step float(v) fetches BITWISE."""
    from dinov3_tpu.train import put_batch

    extra = ["parallel.data=-1", "telemetry.flush_every=3"]
    setup_o, batch = _setup(extra, 8, eight_devices)
    d = put_batch(batch, setup_o.batch_shardings)
    oracle = {}
    state = setup_o.state
    for it in range(5):
        state, metrics = setup_o.step_fn(
            state, d, setup_o.scalars(it), jax.random.key(1))
        oracle[it] = {k: float(v) for k, v in metrics.items()}

    setup_r, _ = _setup(extra, 8, eight_devices)
    plan = setup_r.telemetry()
    assert plan is not None and plan.ring_len == 3
    assert plan.metric_names == sorted(oracle[0])
    ring = plan.init_ring()
    reader = plan.reader()
    state = setup_r.state
    flushed: dict = {}
    for it in range(5):
        state, ring = plan.step_fn(
            state, ring, d, setup_r.scalars(it), jax.random.key(1))
        if it in (2, 4):
            its, rows, streak = reader.flush(ring, it + 1)
            assert streak == 0
            for j, row_it in enumerate(its):
                flushed[int(row_it)] = dict(zip(plan.metric_names, rows[j]))
    assert set(flushed) == set(oracle)
    for it in oracle:
        for k, want in oracle[it].items():
            assert float(flushed[it][k]) == want, (it, k)


def test_telemetry_step_census_pinned(eight_devices):
    """Copy census of the EXACT compiled telemetry step: the ring
    writes carry the "telemetry" named-scope attribution, the total is
    a small bounded delta over the oracle step, and no new "large"
    copies appear (donation keeps the ring write in place)."""
    from dinov3_tpu.train import put_batch
    from dinov3_tpu.utils import classify_copy, hlo_copy_census

    assert classify_copy(
        ' %dynamic-update-slice.1 = f32[4,6]{1,0} dynamic-update-slice('
        '...), metadata={op_name="jit(step)/telemetry_ring/dus"}'
    ) == "telemetry"

    setup, batch = _setup(["parallel.data=-1", "telemetry.flush_every=4"],
                          8, eight_devices)
    d = put_batch(batch, setup.batch_shardings)
    args_o = (setup.state, d, setup.scalars(0), jax.random.key(0))
    text_o = setup.step_fn.lower(*args_o).compile().as_text()
    plan = setup.telemetry()
    ring = plan.init_ring()
    text_t = plan.step_fn.lower(
        setup.state, ring, d, setup.scalars(0),
        jax.random.key(0)).compile().as_text()

    # the ring write is IN the compiled program under its named scope...
    assert "telemetry_ring" in text_t
    assert "telemetry_ring" not in text_o
    census_o = hlo_copy_census(text_o)
    census_t = hlo_copy_census(text_t)
    # ...and costs at most a handful of attributed copy ops: this
    # backend FUSES the two dynamic-update-slices ([1, M] row + [1]
    # stamp) into the step's fusions (0 standalone copy ops — free);
    # a backend that materializes them must land them in the
    # "telemetry" category (classify_copy above), never in
    # small/large/donation
    tele = census_t["by_category"].get("telemetry", {"ops": 0, "bytes": 0})
    assert tele["ops"] <= 8, census_t["by_category"]
    # census ceiling: no copy regression vs the oracle step beyond the
    # attributed telemetry writes and a few scheduling copies
    assert census_t["hlo_copy_total"] <= census_o["hlo_copy_total"] + 12, (
        census_o, census_t)
    large_o = census_o["by_category"].get("large", {"ops": 0})["ops"]
    large_t = census_t["by_category"].get("large", {"ops": 0})["ops"]
    assert large_t <= large_o, (census_o, census_t)


def test_setup_wiring_and_toggle(eight_devices):
    """auto-on: TrainSetup carries a lazy telemetry builder; =false
    selects the oracle (no builder); the plan memoizes."""
    setup, _ = _setup(["telemetry.flush_every=7"], 8, eight_devices)
    assert setup.telemetry_builder is not None
    plan = setup.telemetry()
    assert plan.ring_len == 7 and plan is setup.telemetry()
    assert "total_loss" in plan.metric_names
    off, _ = _setup(["telemetry.async_metrics=false"], 8, eight_devices)
    assert off.telemetry_builder is None and off.telemetry() is None
    cfg = smol_cfg()
    assert telemetry_wished(cfg)
    cfg.telemetry.async_metrics = False
    assert not telemetry_wished(cfg)


# ---------------- the short CPU dryrun of train/train.py ----------------

@pytest.fixture(scope="module")
def tiny_run(tmp_path_factory):
    """One 6-iteration dryrun of the real trainer under async metrics
    (flush_every=4 -> one full + one partial flush), shared by the
    span/heartbeat/benchmark/loss assertions below."""
    from dinov3_tpu.train.train import main as train_main

    out = tmp_path_factory.mktemp("tele_run")
    result = train_main([
        "--output-dir", str(out), "--no-resume",
        "--record-losses", str(out / "losses.jsonl"),
        "--benchmark", "2",
    ] + TINY_TRAIN + [
        "train.OFFICIAL_EPOCH_LENGTH=6", "checkpointing.period=4",
        "telemetry.flush_every=4",
    ])
    return out, result


def test_dryrun_records_every_iteration(tiny_run):
    out, result = tiny_run
    assert result["iterations"] == 6
    assert math.isfinite(result["final_loss"])
    rows = [json.loads(l) for l in open(out / "losses.jsonl")]
    assert [r["iteration"] for r in rows] == list(range(6))
    assert all(math.isfinite(r["total_loss"]) for r in rows)
    # --benchmark produced a number through the explicit fence
    assert result.get("img_per_sec", 0) > 0


def test_dryrun_span_jsonl_schema(tiny_run):
    out, _ = tiny_run
    from dinov3_tpu.telemetry.spans import PHASES

    spans = [json.loads(l)
             for l in open(out / "telemetry" / "spans.jsonl")]
    assert spans, "dryrun must emit spans"
    names = {s["name"] for s in spans}
    # every hot-loop phase that ran appears with the shared vocabulary
    for want in ("data_wait", "h2d", "dispatch", "metrics_flush",
                 "checkpoint_save"):
        assert want in names, names
    for s in spans:
        assert isinstance(s["name"], str) and s["t"] > 0
        if s["name"] in PHASES:
            assert s["dur_ms"] >= 0
            assert s["iteration"] is None or isinstance(s["iteration"], int)
    # memory samples ride the same stream, at setup/compile + flushes
    mem_points = [s["point"] for s in spans if s["name"] == "memory"]
    assert "setup" in mem_points and "compile" in mem_points
    assert mem_points.count("flush") >= 2
    for s in spans:
        if s["name"] == "memory":
            assert all(d["bytes_in_use"] >= 0 for d in s["devices"])


def test_dryrun_heartbeat(tiny_run):
    out, _ = tiny_run
    # role-namespaced since PR 11 (telemetry/watchdog.py keeps the
    # legacy un-namespaced read path for pre-PR-11 output dirs)
    hb = out / "telemetry" / "heartbeat.train"
    assert hb.exists()
    beat = json.loads(hb.read_text())
    assert beat["iteration"] >= 4 and beat["t"] > 0


def test_heartbeat_mtime_advances(tmp_path):
    tracer = SpanTracer(str(tmp_path), heartbeat_every=1)
    tracer.beat(0)
    m0 = os.stat(tracer.heartbeat_path).st_mtime_ns
    time.sleep(0.05)
    tracer.beat(1)
    m1 = os.stat(tracer.heartbeat_path).st_mtime_ns
    assert m1 > m0
    # heartbeat_every gates the touch
    tracer2 = SpanTracer(str(tmp_path / "b"), heartbeat_every=4)
    tracer2.beat(1)
    assert not os.path.exists(tracer2.heartbeat_path)
    tracer2.beat(4)
    assert os.path.exists(tracer2.heartbeat_path)
    tracer.close()
    tracer2.close()


def test_resume_mid_ring_determinism(tmp_path):
    """Kill a run mid flush-window, resume from the checkpoint: the
    resumed run records the same per-iteration losses as the
    uninterrupted one (ring re-anchors at the restored iteration)."""
    from dinov3_tpu.train.train import main as train_main

    common = TINY_TRAIN + [
        "train.OFFICIAL_EPOCH_LENGTH=5", "checkpointing.period=3",
        "telemetry.flush_every=2",
        # --record-losses pins probs_dtype=fp32; the interrupted leg
        # records nothing, so pin it everywhere or the legs would train
        # different programs (the ADVICE-r2 golden-trace rule)
        "compute_precision.probs_dtype=fp32",
    ]

    def losses(path):
        with open(path) as f:
            return {json.loads(l)["iteration"]: json.loads(l)["total_loss"]
                    for l in f if l.strip()}

    a, b = tmp_path / "a", tmp_path / "b"
    train_main(["--output-dir", str(a), "--no-resume",
                "--record-losses", str(a / "l.jsonl")] + common)
    train_main(["--output-dir", str(b), "--no-resume",
                "--max-iterations", "3"] + common)
    out = train_main(["--output-dir", str(b),
                      "--record-losses", str(b / "l.jsonl")] + common)
    assert out["iterations"] == 5
    la, lb = losses(a / "l.jsonl"), losses(b / "l.jsonl")
    assert set(la) == set(range(5))
    assert set(lb) == {3, 4}, "resume must start at the restored step"
    for it in (3, 4):
        assert la[it] == pytest.approx(lb[it], rel=1e-6), (
            f"iteration {it}: uninterrupted {la[it]} != resumed {lb[it]}")


# ---------------- --benchmark explicit fence ----------------

def test_step_timer_window():
    t = StepTimer(3, 10)
    assert [it for it in range(10) if t.active(it)] == [6, 7, 8, 9]
    assert not StepTimer(0, 10).active(9)


def test_bench_fence_agrees_with_freeride_on_oracle(eight_devices):
    """On the oracle path (per-step metrics fetch still present) the
    explicit tiny-fetch fence and the old free-ride-on-the-fetch timing
    measure the same intervals: the fence lands after the fetch already
    synced the step, so the two timestamp streams differ by ~the cost
    of one 4-byte fetch."""
    from dinov3_tpu.train import put_batch

    setup, batch = _setup(["telemetry.async_metrics=false"], 8,
                          eight_devices)
    assert setup.telemetry() is None
    d = put_batch(batch, setup.batch_shardings)
    state = setup.state
    timer = StepTimer(2, 4)
    freeride = []
    for it in range(4):
        state, metrics = setup.step_fn(
            state, d, setup.scalars(it), jax.random.key(0))
        float(metrics["total_loss"])  # the oracle's per-step sync
        if timer.active(it):
            freeride.append(time.perf_counter())
            timer.mark(state)
    assert timer.n_intervals == len(freeride) - 1 == 2
    for j in range(timer.n_intervals):
        fence_iv = timer.times[j + 1] - timer.times[j]
        free_iv = freeride[j + 1] - freeride[j]
        assert abs(fence_iv - free_iv) < 0.10 * max(fence_iv, free_iv) \
            + 0.01, (fence_iv, free_iv)


# ---------------- guardrail ----------------

def test_warn_telemetry_flush_period():
    from dinov3_tpu.configs.config import warn_telemetry_flush_period

    cfg = smol_cfg(["checkpointing.period=100",
                    "evaluation.eval_period_iterations=200"])
    cfg.telemetry.flush_every = 50
    assert warn_telemetry_flush_period(cfg) is None
    cfg.telemetry.flush_every = 150
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        msg = warn_telemetry_flush_period(cfg)
    assert msg and "checkpointing.period=100" in msg
    assert "eval" not in msg.split("exceeds")[1].split("—")[0]
    assert any("telemetry flush window" in str(w.message) for w in caught)
    cfg.telemetry.flush_every = 250
    msg = warn_telemetry_flush_period(cfg)
    assert "checkpointing.period=100" in msg \
        and "eval_period_iterations=200" in msg
    # oracle arm holds no rows on device: no warning
    cfg.telemetry.async_metrics = False
    assert warn_telemetry_flush_period(cfg) is None


# ---------------- instruments ----------------

def test_blocking_fetch_counter():
    host_sync_stats(reset=True)
    x = jnp.arange(8.0)
    out = blocking_fetch({"a": x, "b": x * 2})
    np.testing.assert_array_equal(out["a"], np.arange(8.0))
    s = host_sync_stats(reset=True)
    assert s["fetches"] == 1 and s["blocked_ms"] >= 0
    assert host_sync_stats()["fetches"] == 0


def test_memory_instruments(eight_devices):
    sm = sample_memory(eight_devices)
    assert len(sm["devices"]) == 8
    for d in sm["devices"]:
        assert d["source"] in ("memory_stats", "live_arrays")
        assert d["bytes_in_use"] >= 0
    x = jax.device_put(np.zeros((4, 4), np.float32), eight_devices[0])
    rec = per_device_state_bytes({"x": x})
    assert rec["max_per_device"] == 64 and rec["total"] == 64


def test_loss_tools_consume_flushed_batches(tmp_path):
    from dinov3_tpu.logging_utils import MetricLogger
    from dinov3_tpu.utils import LossComparator, LossRecorder

    names = ["aux", "total_loss"]
    its = np.array([3, 4, 5])
    rows = np.array([[0.5, 1.5], [0.25, 1.25], [0.125, 1.125]], np.float32)
    path = tmp_path / "rec.jsonl"
    rec = LossRecorder(str(path))
    rec.record_batch(its, names, rows)
    rec.close()
    got = [json.loads(l) for l in open(path)]
    assert [g["iteration"] for g in got] == [3, 4, 5]
    assert got[1]["total_loss"] == 1.25

    comp = LossComparator(str(path))
    assert comp.check_batch(its, names, rows)
    assert comp.n_diverged == 0
    bad = rows.copy()
    bad[2, 1] = 9.0
    assert not comp.check_batch(its, names, bad)
    assert comp.n_diverged == 1

    ml = MetricLogger()
    ml.consume_flush(names, its, rows,
                     scheds=lambda i: {"lr": 0.1 * i})
    assert ml.meters["total_loss"].count == 3
    assert ml.meters["total_loss"].value == pytest.approx(1.125)
    assert ml.meters["lr"].value == pytest.approx(0.5)


# ---------------- preemption chain + heartbeat scan (ISSUE 19) ----------------

def test_scan_heartbeats_mixed_legacy_and_namespaced(tmp_path):
    """A dir holding BOTH pre-PR-11 un-namespaced heartbeats and
    namespaced ones: legacy files report role "train" with
    ``legacy=True``, a namespaced beat shadows the legacy file for the
    same (role, rank), and staleness is judged per file."""
    from dinov3_tpu.telemetry import scan_heartbeats

    tdir = tmp_path / "telemetry"
    os.makedirs(tdir)
    now = time.time()
    for name, age in [
        ("heartbeat", 100.0),          # legacy (train, 0) — shadowed
        ("heartbeat.train", 1.0),      # namespaced (train, 0) — fresh
        ("heartbeat.rank1", 50.0),     # legacy (train, 1) — survives
        ("heartbeat.serve.rank2", 2.0),
    ]:
        p = tdir / name
        p.write_text("beat\n")
        os.utime(p, (now - age, now - age))

    rows = scan_heartbeats(str(tmp_path), stale_after_s=10.0, now=now)
    by_key = {(r["role"], r["rank"]): r for r in rows}
    assert set(by_key) == {("serve", 2), ("train", 0), ("train", 1)}
    t0 = by_key[("train", 0)]
    assert not t0["legacy"] and not t0["stalled"]  # namespaced shadows
    assert t0["path"].endswith("heartbeat.train")
    t1 = by_key[("train", 1)]
    assert t1["legacy"] and t1["stalled"]
    assert not by_key[("serve", 2)]["stalled"]


def test_preempt_chain_spans_roundtrip(tmp_path):
    """preempt_notice -> preempt_save -> resume_restore: each link
    emitted through the tracer lands in the span JSONL with the chain
    schema, and ``last_preempt_record`` recovers the newest save record
    across streams even past a torn trailing line (the usual state of a
    preempted writer's file)."""
    from dinov3_tpu.telemetry import (
        PREEMPT_CHAIN,
        SpanTracer,
        emit_preempt_chain,
        last_preempt_record,
    )

    assert PREEMPT_CHAIN == (
        "preempt_notice", "preempt_save", "resume_restore")

    tracer = SpanTracer(str(tmp_path), flush_every_emits=1)
    emit_preempt_chain(tracer, "preempt_notice", 7, signal="SIGTERM",
                       dur_ms=3.5)
    emit_preempt_chain(tracer, "preempt_save", 7, step=8, dur_ms=42.0)
    tracer.close()

    # a second (serve-role) stream with an older save + a torn line
    serve = SpanTracer(str(tmp_path), role="serve", flush_every_emits=1)
    rec = emit_preempt_chain(serve, "preempt_save", 3, step=4)
    serve.close()
    with open(serve.spans_path, "a") as f:
        f.write('{"name": "preempt_save", "t": 9')  # torn mid-record

    # hand the older record an earlier clock so "newest" is meaningful
    lines = [json.loads(l) for l in open(serve.spans_path).readlines()[:-1]]
    lines[0]["t"] = rec["t"] - 60.0
    with open(serve.spans_path, "w") as f:
        for l in lines:
            f.write(json.dumps(l) + "\n")
        f.write('{"name": "preempt_save", "t": 9')

    got = last_preempt_record(str(tmp_path))
    assert got["name"] == "preempt_save" and got["step"] == 8
    assert got["iteration"] == 7 and got["role"] == "train"
    notice = last_preempt_record(str(tmp_path), "preempt_notice")
    assert notice["signal"] == "SIGTERM"
    assert last_preempt_record(str(tmp_path), "resume_restore") is None

    # tracer=None (spans disabled): record still built for the caller
    off = emit_preempt_chain(None, "resume_restore", 0, path="disk")
    assert off["path"] == "disk" and "t" in off
    with pytest.raises(AssertionError):
        emit_preempt_chain(None, "not_a_link", 0)


def test_preemption_handler_manual_notice():
    """PreemptionHandler.notice() — the programmatic path chaos
    harnesses use — trips the same stop + first-notice clock the signal
    path records."""
    from dinov3_tpu.run.preemption import PreemptionHandler

    h = PreemptionHandler()  # signal hooks only install in __enter__
    assert not h.should_stop() and h.notice_time is None
    t0 = time.time()
    h.notice("chaos_kill")
    assert h.should_stop() and h.notice_signal == "chaos_kill"
    assert h.notice_time is not None and h.notice_time >= t0
    first = h.notice_time
    h.notice("second")  # later notices keep the FIRST clock
    assert h.notice_time == first and h.notice_signal == "chaos_kill"
