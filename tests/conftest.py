"""Test harness: 8 virtual CPU devices so every collective / sharding test
runs a real multi-device mesh without hardware (SURVEY.md §4 implication (a))."""

import os

# Hard-override: the ambient env may pin JAX_PLATFORMS=axon (the tunneled
# TPU); the test suite always runs on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported by pytest plugins (jaxtyping/typeguard), in
# which case the env vars above were captured too late — but the backend is
# not initialized until first use, so config updates still take effect.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jaxlibs predate jax_num_cpu_devices; the XLA_FLAGS
    # force_host_platform_device_count set above covers them
    pass
jax.config.update("jax_default_matmul_precision", "highest")
# the suite is compile-dominated; persist compiles across runs (keyed by
# compiler fingerprint, so a jaxlib upgrade invalidates cleanly). Per-uid
# path: a world-shared one turns into silent permission-denied no-ops for
# the second user on a shared host
import tempfile

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(tempfile.gettempdir(), f"jaxcache_cpu_tests_{os.getuid()}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.key(0)
