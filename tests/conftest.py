"""Test harness: 8 virtual CPU devices so every collective / sharding test
runs a real multi-device mesh without hardware (SURVEY.md §4 implication (a))."""

import os

# Hard-override: the ambient env may pin JAX_PLATFORMS=axon (the tunneled
# TPU); the test suite always runs on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported by pytest plugins (jaxtyping/typeguard), in
# which case the env vars above were captured too late — but the backend is
# not initialized until first use, so config updates still take effect.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jaxlibs predate jax_num_cpu_devices; the XLA_FLAGS
    # force_host_platform_device_count set above covers them
    pass
jax.config.update("jax_default_matmul_precision", "highest")
# the suite is compile-dominated; persist compiles across runs (keyed by
# compiler fingerprint, so a jaxlib upgrade invalidates cleanly). Per-uid
# path: a world-shared one turns into silent permission-denied no-ops for
# the second user on a shared host
import tempfile

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(tempfile.gettempdir(), f"jaxcache_cpu_tests_{os.getuid()}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402

# ---- per-version numeric tolerances ----
# The suite was developed against jax >= 0.5; this container floor is
# jax 0.4.37 / jaxlib 0.4.36, whose XLA:CPU fuses the pipeline stage
# scan and the GSPMD collectives differently, producing tolerance-level
# numeric skew on the cross-program equivalence tests (measured there:
# max rel 1.9e-3 pipelined forward, 6.4e-5 intermediate layers, 8.4e-4
# sharded-vs-single loss). The strict tolerances stay pinned on current
# jax; the legacy ones are documented measurements x ~3 headroom, NOT
# open-ended fudge.
import jaxlib  # noqa: E402

try:
    _JAXLIB_VERSION = tuple(int(x) for x in jaxlib.__version__.split(".")[:3])
except ValueError:
    _JAXLIB_VERSION = (99,)
LEGACY_JAXLIB = _JAXLIB_VERSION < (0, 5, 0)


def legacy_tol(strict: float, legacy: float) -> float:
    """Pick the numeric tolerance for this jaxlib (see comment above)."""
    return legacy if LEGACY_JAXLIB else strict


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.key(0)
