"""Test harness: 8 virtual CPU devices so every collective / sharding test
runs a real multi-device mesh without hardware (SURVEY.md §4 implication (a))."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.key(0)
