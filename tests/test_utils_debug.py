"""Parameter counting, loss record/compare, weight dumps, in-train bench
(the reference declared these debug flags but never wired them)."""

import json

import pytest

import numpy as np

from dinov3_tpu.utils import (
    LossComparator,
    LossRecorder,
    count_parameters,
    dump_weights,
    format_parameter_counts,
)

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=3", "optim.epochs=1",
    "optim.warmup_epochs=0", "optim.scaling_rule=none",
    "data.backend=synthetic",
]


def test_count_parameters_by_submodule():
    params = {"student": {"w": np.zeros((3, 4)), "b": np.zeros((4,))},
              "teacher": {"w": np.zeros((3, 4))}}
    counts = count_parameters(params)
    assert counts == {"student": 16, "teacher": 12, "total": 28}
    table = format_parameter_counts(counts)
    assert "student" in table and "total" in table


def test_loss_record_then_compare_roundtrip(tmp_path):
    path = str(tmp_path / "losses.jsonl")
    rec = LossRecorder(path)
    rec.record(0, {"total_loss": 1.5, "dino": 0.5})
    rec.record(1, {"total_loss": 1.25, "dino": 0.4})
    rec.close()
    rows = [json.loads(x) for x in open(path)]
    assert rows[1]["total_loss"] == 1.25

    cmp = LossComparator(path)
    assert cmp.check(0, {"total_loss": 1.5, "dino": 0.5})
    assert cmp.check(1, {"total_loss": 1.25, "dino": 0.4})
    assert cmp.n_diverged == 0
    # a diverging value is caught
    cmp2 = LossComparator(path)
    assert not cmp2.check(0, {"total_loss": 2.0, "dino": 0.5})
    assert cmp2.n_diverged == 1 and "total_loss" in cmp2.summary()


def test_dump_weights_flat_npz(tmp_path):
    path = str(tmp_path / "w.npz")
    dump_weights(path, {"a": {"b": np.ones((2, 2))}, "c": np.zeros((3,))})
    loaded = np.load(path)
    assert set(loaded.files) == {"a/b", "c"}
    np.testing.assert_array_equal(loaded["a/b"], np.ones((2, 2)))


@pytest.mark.slow
def test_trainer_record_compare_benchmark_flags(tmp_path):
    from dinov3_tpu.train.train import main

    rec_path = str(tmp_path / "ref.jsonl")
    out1 = main([
        "--output-dir", str(tmp_path / "r1"), "--no-resume",
        "--record-losses", rec_path,
        "--dump-weights", str(tmp_path / "final.npz"),
        "--benchmark", "2",
        *SMOL,
    ])
    assert out1["iterations"] == 3
    assert "img_per_sec" in out1
    assert (tmp_path / "final.npz").exists()
    assert len(open(rec_path).readlines()) == 3

    # identical seed/config -> zero divergences against the recording
    out2 = main([
        "--output-dir", str(tmp_path / "r2"), "--no-resume",
        "--ref-losses", rec_path,
        *SMOL,
    ])
    assert out2["loss_divergences"] == 0


def test_self_check_flag(tmp_path):
    from dinov3_tpu.train.train import main

    out = main([
        "--output-dir", str(tmp_path / "sc"), "--self-check", "--no-resume",
        *SMOL,
    ])
    assert out["self_check_failures"] == 0
    assert out["check/step_counter_advances"] is True
    assert any(k.startswith("check/teacher_ema_moves") for k in out)
