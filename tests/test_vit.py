import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import get_default_config, apply_dot_overrides
from dinov3_tpu.models import build_backbone, build_model_from_cfg
from dinov3_tpu.models.vision_transformer import DinoVisionTransformer, vit_test

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)
TINY = dict(embed_dim=32, n_blocks=2, num_heads=2, ffn_ratio=2.0,
            patch_size=4, attn_impl="xla", **F32)


def tiny(**kw):
    return DinoVisionTransformer(**{**TINY, **kw})


def test_forward_features_shapes():
    m = tiny(n_storage_tokens=3, layerscale_init=1e-5)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    params = m.init(jax.random.key(1), x)
    out = m.apply(params, x)
    assert out["x_norm_clstoken"].shape == (2, 32)
    assert out["x_storage_tokens"].shape == (2, 3, 32)
    assert out["x_norm_patchtokens"].shape == (2, 16, 32)
    assert out["x_prenorm"].shape == (2, 1 + 3 + 16, 32)


def test_mask_tokens_change_output():
    m = tiny()
    x = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    params = m.init(jax.random.key(1), x)
    masks = jnp.zeros((2, 16), bool).at[:, :8].set(True)
    out_masked = m.apply(params, x, masks)
    out_plain = m.apply(params, x)
    assert not np.allclose(
        np.asarray(out_masked["x_norm_patchtokens"]),
        np.asarray(out_plain["x_norm_patchtokens"]),
    )


def test_resolution_agnostic_rope():
    """Same params must run any crop resolution (multi-crop requirement)."""
    m = tiny()
    x224 = jax.random.normal(jax.random.key(0), (1, 16, 16, 3))
    x96 = jax.random.normal(jax.random.key(1), (1, 8, 8, 3))
    params = m.init(jax.random.key(2), x224)
    out_g = m.apply(params, x224)
    out_l = m.apply(params, x96)
    assert out_g["x_norm_patchtokens"].shape == (1, 16, 32)
    assert out_l["x_norm_patchtokens"].shape == (1, 4, 32)


def test_untied_norms_used_for_local_crops():
    m = tiny(untie_cls_and_patch_norms=True, untie_global_and_local_cls_norm=True)
    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 3))
    params = nn.meta.unbox(m.init(jax.random.key(1), x))
    p = params["params"]
    assert "cls_norm" in p and "local_cls_norm" in p and "norm" in p
    # make local_cls_norm distinguishable
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    flat[("params", "local_cls_norm", "scale")] = (
        flat[("params", "local_cls_norm", "scale")] * 5.0
    )
    params2 = flax.traverse_util.unflatten_dict(flat)
    out_global = m.apply(params2, x, crop_kind="global", deterministic=False,
                         rngs={"drop_path": jax.random.key(2)})
    out_local = m.apply(params2, x, crop_kind="local", deterministic=False,
                        rngs={"drop_path": jax.random.key(2)})
    assert not np.allclose(np.asarray(out_global["x_norm_clstoken"]),
                           np.asarray(out_local["x_norm_clstoken"]))
    # patch tokens share the patch norm either way
    np.testing.assert_allclose(np.asarray(out_global["x_norm_patchtokens"]),
                               np.asarray(out_local["x_norm_patchtokens"]),
                               atol=1e-6)


def test_scan_layers_matches_loop():
    """Scanned stack must compute the same function family (same shapes,
    deterministic forward) as the unrolled loop given transplanted params."""
    m_loop = tiny(n_blocks=3)
    m_scan = tiny(n_blocks=3, scan_layers=True)
    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 3))
    p_loop = nn.meta.unbox(m_loop.init(jax.random.key(1), x))
    p_scan = nn.meta.unbox(m_scan.init(jax.random.key(1), x))
    import flax

    flat_loop = flax.traverse_util.flatten_dict(p_loop["params"])
    flat_scan = flax.traverse_util.flatten_dict(p_scan["params"])
    # transplant loop params into the scan stack (stack blocks_i leaves)
    stacked = {}
    for k, v in flat_scan.items():
        if k[0] == "blocks":
            # scan tree: ("blocks", "block", ...); loop tree: (f"blocks_{i}", ...)
            per_layer = [
                flat_loop[(f"blocks_{i}",) + k[2:]] for i in range(3)
            ]
            stacked[k] = jnp.stack(per_layer, axis=0)
        else:
            stacked[k] = flat_loop[k]
        assert stacked[k].shape == v.shape, (k, stacked[k].shape, v.shape)
    p_scan2 = {"params": flax.traverse_util.unflatten_dict(stacked)}
    out_loop = m_loop.apply(p_loop, x)
    out_scan = m_scan.apply(p_scan2, x)
    np.testing.assert_allclose(
        np.asarray(out_loop["x_norm_clstoken"]),
        np.asarray(out_scan["x_norm_clstoken"]),
        atol=1e-5,
    )


def test_get_intermediate_layers():
    m = tiny(n_blocks=3)
    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 3))
    params = m.init(jax.random.key(1), x)
    outs = m.apply(params, x, n=2, reshape=True,
                   return_class_token=True,
                   method=DinoVisionTransformer.get_intermediate_layers)
    assert len(outs) == 2
    patches, cls = outs[0]
    assert patches.shape == (2, 32, 2, 2)
    assert cls.shape == (2, 32)


def test_build_from_cfg():
    cfg = get_default_config()
    apply_dot_overrides(cfg, ["student.arch=vit_test", "student.patch_size=4",
                              "student.drop_path_rate=0.2"])
    student, teacher, dim = build_model_from_cfg(cfg)
    assert dim == 64
    assert student.drop_path_rate == 0.2
    assert teacher.drop_path_rate == 0.0  # teacher never drops paths
    assert teacher.pos_embed_rope_jitter_coords is None


def test_arch_ladder_dims():
    from dinov3_tpu.models import vit_7b, vit_giant2, vit_large, vit_so400m

    l = vit_large()
    assert (l.embed_dim, l.n_blocks, l.num_heads) == (1024, 24, 16)
    g = vit_giant2()
    assert (g.embed_dim, g.n_blocks, g.num_heads) == (1536, 40, 24)
    b7 = vit_7b()
    assert (b7.embed_dim, b7.n_blocks, b7.num_heads, b7.ffn_ratio) == (4096, 40, 32, 3.0)
    so = vit_so400m()
    assert (so.embed_dim, so.n_blocks, so.num_heads) == (1152, 27, 18)


def test_get_intermediate_layers_scan():
    """Scan-over-blocks models support intermediate-feature extraction via
    scan ys; results match the unrolled loop given transplanted params."""
    import flax

    m_loop = tiny(n_blocks=3)
    m_scan = tiny(n_blocks=3, scan_layers=True)
    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 3))
    p_loop = nn.meta.unbox(m_loop.init(jax.random.key(1), x))
    flat_loop = flax.traverse_util.flatten_dict(p_loop["params"])
    p_scan = nn.meta.unbox(m_scan.init(jax.random.key(1), x))
    flat_scan = flax.traverse_util.flatten_dict(p_scan["params"])
    stacked = {}
    for k, v in flat_scan.items():
        if k[0] == "blocks":
            stacked[k] = jnp.stack(
                [flat_loop[(f"blocks_{i}",) + k[2:]] for i in range(3)], axis=0
            )
        else:
            stacked[k] = flat_loop[k]
    p_scan2 = {"params": flax.traverse_util.unflatten_dict(stacked)}

    kw = dict(n=2, return_class_token=True,
              method=DinoVisionTransformer.get_intermediate_layers)
    outs_loop = m_loop.apply(p_loop, x, **kw)
    outs_scan = m_scan.apply(p_scan2, x, **kw)
    assert len(outs_scan) == len(outs_loop) == 2
    for (pl, cl), (ps, cs) in zip(outs_loop, outs_scan):
        np.testing.assert_allclose(np.asarray(pl), np.asarray(ps), atol=1e-5)
        np.testing.assert_allclose(np.asarray(cl), np.asarray(cs), atol=1e-5)


def test_get_intermediate_layers_untied_norms_multi():
    """n>1 with untied cls/patch norms (large-model recipes) must not
    raise a flax name collision."""
    m = tiny(n_blocks=3, untie_cls_and_patch_norms=True)
    x = jax.random.normal(jax.random.key(0), (2, 8, 8, 3))
    params = m.init(jax.random.key(1), x)
    outs = m.apply(params, x, n=2,
                   method=DinoVisionTransformer.get_intermediate_layers)
    assert len(outs) == 2


def test_get_intermediate_layers_rejects_bad_indices():
    m = tiny(n_blocks=3, scan_layers=True)
    x = jax.random.normal(jax.random.key(0), (1, 8, 8, 3))
    params = m.init(jax.random.key(1), x)
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        m.apply(params, x, n=[3],
                method=DinoVisionTransformer.get_intermediate_layers)
