"""Job context and preemption handler."""

import os
import signal

import pytest

from dinov3_tpu.run import PreemptionHandler, job_context


def test_preemption_handler_sets_flag():
    with PreemptionHandler(signals=(signal.SIGUSR1,)) as h:
        assert not h.should_stop()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert h.should_stop()
    # handler restored afterwards
    assert signal.getsignal(signal.SIGUSR1) != h._handle


def test_job_context_creates_output_and_logs(tmp_path):
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    out = tmp_path / "job"
    apply_dot_overrides(cfg, [f"train.output_dir={out}"])
    with job_context(cfg, name="unit"):
        pass
    assert (out / "config.yaml").exists()


def test_build_sbatch_script_directives(tmp_path):
    from dinov3_tpu.run import build_sbatch_script

    target = tmp_path / "trainer.py"
    target.write_text("def main(argv):\n    pass\n")
    script = build_sbatch_script(
        module_path=str(target),
        script_args=["--config-file", "c.yaml", "optim.epochs=1"],
        output_dir=str(tmp_path),
        nodes=4,
        partition="tpu",
        account="acct",
        qos="high",
        comment="hello world",
        signal_grace_s=90,
    )
    assert "#SBATCH --nodes=4" in script
    assert "#SBATCH --requeue" in script
    assert "#SBATCH --signal=TERM@90" in script
    assert "#SBATCH --partition=tpu" in script
    assert "JAX_COORDINATOR_ADDRESS" in script
    assert "JAX_PROCESS_ID" in script
    assert "initialize_distributed" in script
    assert "optim.epochs=1" in script


def test_submit_job_writes_script_without_sbatch(tmp_path, monkeypatch):
    from dinov3_tpu.run import build_sbatch_script, submit_job

    monkeypatch.setenv("PATH", "")  # no sbatch on PATH
    target = tmp_path / "trainer.py"
    target.write_text("def main(argv):\n    pass\n")
    script = build_sbatch_script(
        module_path=str(target), script_args=[], output_dir=str(tmp_path)
    )
    job_id = submit_job(script, str(tmp_path))
    assert job_id is None
    assert (tmp_path / "job.sbatch").read_text() == script


def test_load_callable(tmp_path):
    from dinov3_tpu.run import load_callable

    target = tmp_path / "mod.py"
    target.write_text("def entry(argv):\n    return list(argv) + ['ok']\n")
    fn = load_callable(str(target), "entry")
    assert fn(["a"]) == ["a", "ok"]


def test_local_launcher_two_processes(tmp_path):
    from dinov3_tpu.run import LocalLauncher

    target = tmp_path / "prog.py"
    target.write_text(
        "import jax\n"
        "def main(argv):\n"
        "    import pathlib\n"
        "    n = jax.process_count()\n"
        "    assert n == 2, n\n"
        "    pathlib.Path(argv[0] + f'/done{jax.process_index()}').touch()\n"
    )
    LocalLauncher(2, port=12457).launch(
        str(target), [str(tmp_path)], timeout_s=120.0
    )
    assert (tmp_path / "done0").exists()
    assert (tmp_path / "done1").exists()


def test_local_launcher_fails_fast_on_child_error(tmp_path):
    import time

    from dinov3_tpu.run import LocalLauncher

    target = tmp_path / "bad.py"
    target.write_text("def main(argv):\n    raise SystemExit(3)\n")
    t0 = time.monotonic()
    try:
        LocalLauncher(2, port=12473).launch(
            str(target), [], timeout_s=300.0
        )
        raised = False
    except RuntimeError as e:
        raised = True
        assert "3" in str(e)
    assert raised
    # far less than the 300s deadline: the group was killed on first failure
    assert time.monotonic() - t0 < 120


@pytest.mark.slow
def test_local_launcher_multiprocess_training(tmp_path):
    """Two coordinated processes form a data=2 mesh and train end-to-end —
    the multi-host path the reference stubbed out (its get_rank() was
    hardcoded to 0, SURVEY.md §2.5)."""
    from dinov3_tpu.run import LocalLauncher

    target = tmp_path / "train2.py"
    target.write_text(
        "def main(argv):\n"
        "    import jax\n"
        "    assert jax.process_count() == 2\n"
        "    import hashlib, pathlib\n"
        "    from dinov3_tpu.configs import load_config\n"
        "    from dinov3_tpu.train.train import build_data_iterator\n"
        "    cfg = load_config(None, overrides=[a for a in argv if '=' in a])\n"
        "    rank = jax.process_index()\n"
        "    b = next(build_data_iterator(cfg, 4, rank=rank, world_size=2))\n"
        "    # each host loads only its half of the global batch...\n"
        "    assert b['global_crops'].shape[0] == 4, b['global_crops'].shape\n"
        "    digest = hashlib.sha256(b['global_crops'].tobytes()).hexdigest()\n"
        "    pathlib.Path(argv[1]).mkdir(parents=True, exist_ok=True)\n"
        "    pathlib.Path(argv[1] + f'/shard{rank}').write_text(digest)\n"
        "    from dinov3_tpu.train.train import main as train_main\n"
        "    out = train_main(argv)\n"
        "    assert out['iterations'] == 2, out\n"
        "    pathlib.Path(argv[1] + f'/ok{rank}').touch()\n"
    )
    run_dir = tmp_path / "run"
    LocalLauncher(2, port=12481).launch(
        str(target),
        [
            "--output-dir", str(run_dir),
            "--no-resume",
            "student.arch=vit_test", "student.patch_size=4",
            "crops.global_crops_size=16", "crops.local_crops_size=8",
            "crops.local_crops_number=2",
            "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
            "dino.head_bottleneck_dim=16",
            "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
            "ibot.head_bottleneck_dim=16",
            "train.batch_size_per_device=2",
            "train.OFFICIAL_EPOCH_LENGTH=2",
            "optim.epochs=1", "optim.warmup_epochs=0",
            "optim.scaling_rule=none", "data.backend=synthetic",
        ],
        timeout_s=420.0,
    )
    assert (run_dir / "ok0").exists() and (run_dir / "ok1").exists()
    # ...and the halves are disjoint (different content per host)
    assert (run_dir / "shard0").read_text() != (run_dir / "shard1").read_text()
