"""Job context and preemption handler."""

import os
import signal

from dinov3_tpu.run import PreemptionHandler, job_context


def test_preemption_handler_sets_flag():
    with PreemptionHandler(signals=(signal.SIGUSR1,)) as h:
        assert not h.should_stop()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert h.should_stop()
    # handler restored afterwards
    assert signal.getsignal(signal.SIGUSR1) != h._handle


def test_job_context_creates_output_and_logs(tmp_path):
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    out = tmp_path / "job"
    apply_dot_overrides(cfg, [f"train.output_dir={out}"])
    with job_context(cfg, name="unit"):
        pass
    assert (out / "config.yaml").exists()
