import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dinov3_tpu.losses import (
    dino_loss,
    gram_loss,
    ibot_patch_loss_dense,
    ibot_patch_loss_masked,
    koleo_loss,
    sinkhorn_knopp,
    softmax_center_teacher,
    update_center,
)


# ---------------- sinkhorn ----------------

def test_sinkhorn_marginals():
    logits = jax.random.normal(jax.random.key(0), (16, 8))
    q = sinkhorn_knopp(logits, temperature=0.5)
    # each sample's assignment sums to ~1 (last step is the sample marginal)
    np.testing.assert_allclose(np.asarray(q.sum(-1)), 1.0, atol=1e-3)
    # prototype marginal approaches uniform B/K (3 truncated iterations)
    np.testing.assert_allclose(np.asarray(q.sum(0)), 16 / 8, rtol=0.1)
    assert np.asarray(q).min() >= 0
    # extreme logits stay finite and normalized (log-domain guard)
    q2 = sinkhorn_knopp(jax.random.normal(jax.random.key(1), (16, 8)) * 300, 0.05)
    assert np.isfinite(np.asarray(q2)).all()
    np.testing.assert_allclose(np.asarray(q2.sum(-1)), 1.0, atol=1e-3)


def test_sinkhorn_shift_invariance_and_overflow_guard():
    logits = jax.random.normal(jax.random.key(0), (8, 4))
    q1 = sinkhorn_knopp(logits, 0.1)
    q2 = sinkhorn_knopp(logits + 1000.0, 0.1)  # would overflow exp without guard
    np.testing.assert_allclose(np.asarray(q1), np.asarray(q2), atol=1e-4)
    assert np.isfinite(np.asarray(q2)).all()


def test_sinkhorn_padded_rows_ignored():
    logits = jax.random.normal(jax.random.key(0), (12, 6))
    valid = jnp.array([1.0] * 8 + [0.0] * 4)
    q_pad = sinkhorn_knopp(logits, 0.1, row_weights=valid)
    q_ref = sinkhorn_knopp(logits[:8], 0.1)
    np.testing.assert_allclose(np.asarray(q_pad[:8]), np.asarray(q_ref), atol=1e-4)
    # padded rows contribute zero mass
    np.testing.assert_allclose(np.asarray(q_pad[8:]), 0.0, atol=1e-6)


def test_sinkhorn_bf16_storage_close_to_fp32():
    """compute_precision.target_dtype=bf16: the bf16-stored iterate/targets
    track the fp32 path (reductions accumulate fp32 either way)."""
    logits = (jax.random.normal(jax.random.key(0), (64, 512)) * 8).astype(
        jnp.bfloat16)
    q32 = sinkhorn_knopp(logits, 0.07)
    qbf = sinkhorn_knopp(logits, 0.07, storage_dtype=jnp.bfloat16)
    assert qbf.dtype == jnp.bfloat16
    assert q32.dtype == jnp.float32
    # row marginals still ~1 despite bf16 storage (sums accumulate fp32)
    np.testing.assert_allclose(
        np.asarray(qbf.astype(jnp.float32).sum(-1)), 1.0, atol=2e-2)
    # targets agree where they carry mass: total-variation distance per
    # row stays below 1% (tiny tail probs have large *relative* bf16
    # error by construction — irrelevant to a CE target)
    tv = 0.5 * np.abs(
        np.asarray(qbf, dtype=np.float32) - np.asarray(q32)).sum(-1)
    # typical rows are tight; the sharpest rows see ~2% (bf16 ulp on
    # large-|log q| entries) — the accepted cost of the bf16 mode, which
    # is why target_dtype defaults to fp32
    assert np.median(tv) < 5e-3, np.median(tv)
    assert tv.max() < 5e-2, tv.max()
    # padded-row variant keeps zeros exactly zero in bf16 too
    valid = jnp.array([1.0] * 48 + [0.0] * 16)
    qp = sinkhorn_knopp(logits, 0.07, row_weights=valid,
                        storage_dtype=jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(qp[48:], dtype=np.float32), 0.0)


def test_sinkhorn_sharded_matches_single_device(eight_devices):
    """The GSPMD claim: sharded global-array sinkhorn == single-device."""
    mesh = Mesh(np.array(eight_devices), ("data",))
    logits = jax.random.normal(jax.random.key(0), (32, 16))
    ref = sinkhorn_knopp(logits, 0.07)
    sharded_in = jax.device_put(logits, NamedSharding(mesh, P("data", None)))
    out = jax.jit(lambda l: sinkhorn_knopp(l, 0.07))(sharded_in)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------- dino ----------------

def test_dino_loss_matches_manual():
    S, T, B, K = 2, 2, 4, 8
    sl = jax.random.normal(jax.random.key(0), (S, B, K))
    tp = jax.nn.softmax(jax.random.normal(jax.random.key(1), (T, B, K)) / 0.05)
    got = dino_loss(sl, tp, student_temp=0.1)
    logp = np.asarray(jax.nn.log_softmax(sl / 0.1, axis=-1))
    tpn = np.asarray(tp)
    manual = -sum(
        (tpn[t] * logp[s]).sum() for s in range(S) for t in range(T)
    ) / (B * S * T)
    np.testing.assert_allclose(np.asarray(got), manual, rtol=1e-5)


def test_dino_loss_ignore_diagonal():
    S, T, B, K = 2, 2, 4, 8
    sl = jax.random.normal(jax.random.key(0), (S, B, K))
    tp = jax.nn.softmax(jax.random.normal(jax.random.key(1), (T, B, K)) / 0.05)
    got = dino_loss(sl, tp, student_temp=0.1, ignore_diagonal=True)
    logp = np.asarray(jax.nn.log_softmax(sl / 0.1, axis=-1))
    tpn = np.asarray(tp)
    manual = -sum(
        (tpn[t] * logp[s]).sum() for s in range(S) for t in range(T) if s != t
    ) / (B * S * T - B * min(S, T))
    np.testing.assert_allclose(np.asarray(got), manual, rtol=1e-5)


def test_softmax_center_update():
    logits = jax.random.normal(jax.random.key(0), (16, 8))
    center = jnp.zeros((1, 8))
    probs = softmax_center_teacher(logits, center, 0.07)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, atol=1e-5)
    new_center = update_center(center, logits, momentum=0.9)
    expect = 0.1 * np.asarray(logits).mean(0, keepdims=True)
    np.testing.assert_allclose(np.asarray(new_center), expect, atol=1e-6)


# ---------------- ibot ----------------

def test_ibot_masked_weighting():
    M, K = 8, 6
    s = jax.random.normal(jax.random.key(0), (M, K))
    t = jax.nn.softmax(jax.random.normal(jax.random.key(1), (M, K)), axis=-1)
    # image 0 owns tokens 0..2 (w=1/3), image 1 owns 3..4 (w=1/2), rest padding
    w = jnp.array([1 / 3] * 3 + [1 / 2] * 2 + [0.0] * 3)
    got = ibot_patch_loss_masked(s, t, w, n_images=2, student_temp=0.1)
    logp = np.asarray(jax.nn.log_softmax(s / 0.1, -1))
    tn = np.asarray(t)
    ce = -(tn * logp).sum(-1)
    manual = (ce[:3].mean() + ce[3:5].mean()) / 2
    np.testing.assert_allclose(np.asarray(got), manual, rtol=1e-5)


def test_ibot_dense_matches_masked():
    B, T_, K = 2, 6, 5
    s = jax.random.normal(jax.random.key(0), (B, T_, K))
    t = jax.nn.softmax(jax.random.normal(jax.random.key(1), (B, T_, K)), -1)
    masks = jnp.zeros((B, T_), bool).at[0, :2].set(True).at[1, 1:4].set(True)
    dense = ibot_patch_loss_dense(s, t, masks, 0.1)
    # flatten the masked tokens into a padded buffer
    sm = jnp.concatenate([s[0, :2], s[1, 1:4], jnp.zeros((3, K))])
    tm = jnp.concatenate([t[0, :2], t[1, 1:4], jnp.zeros((3, K))])
    w = jnp.array([1 / 2] * 2 + [1 / 3] * 3 + [0.0] * 3)
    masked = ibot_patch_loss_masked(sm, tm, w, n_images=2, student_temp=0.1)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(masked), rtol=1e-5)


# ---------------- koleo ----------------

def test_koleo_known_geometry():
    # 4 unit vectors: two nearly identical -> tiny NN distance dominates
    x = jnp.array([[1.0, 0.0], [0.9999, 0.0141], [0.0, 1.0], [-1.0, 0.0]])
    loss = koleo_loss(x)
    assert np.asarray(loss) > 0  # -log(small distance) is large positive
    # spreading the points reduces the loss
    x2 = jnp.array([[1.0, 0.0], [0.0, 1.0], [-1.0, 0.0], [0.0, -1.0]])
    assert np.asarray(koleo_loss(x2)) < np.asarray(loss)


def test_koleo_matches_reference_formula():
    x = jax.random.normal(jax.random.key(0), (16, 8))
    got = np.asarray(koleo_loss(x))
    xn = np.asarray(x) / (np.linalg.norm(np.asarray(x), axis=-1, keepdims=True) + 1e-8)
    dots = xn @ xn.T
    np.fill_diagonal(dots, -1)
    nn_idx = dots.argmax(1)
    d = np.linalg.norm(xn - xn[nn_idx], axis=-1) + 1e-8
    manual = -np.log(d + 1e-8).mean()
    np.testing.assert_allclose(got, manual, rtol=1e-4)


def test_koleo_groups_are_independent():
    x = jax.random.normal(jax.random.key(0), (16, 4))
    g1 = koleo_loss(x, group_size=8)
    manual = (np.asarray(koleo_loss(x[:8])) + np.asarray(koleo_loss(x[8:]))) / 2
    np.testing.assert_allclose(np.asarray(g1), manual, rtol=1e-5)


def test_koleo_topk():
    x = jax.random.normal(jax.random.key(0), (8, 4))
    l1 = koleo_loss(x, topk=1)
    l3 = koleo_loss(x, topk=3)
    assert not np.allclose(np.asarray(l1), np.asarray(l3))


# ---------------- gram ----------------

def test_gram_zero_for_identical():
    f = jax.random.normal(jax.random.key(0), (2, 5, 8))
    np.testing.assert_allclose(np.asarray(gram_loss(f, f)), 0.0, atol=1e-10)


def test_gram_img_vs_batch_level():
    s = jax.random.normal(jax.random.key(0), (2, 4, 8))
    t = jax.random.normal(jax.random.key(1), (2, 4, 8))
    img = gram_loss(s, t, img_level=True)
    batch = gram_loss(s, t, img_level=False)
    assert not np.allclose(np.asarray(img), np.asarray(batch))


def test_gram_neg_clipping_modes():
    s = jax.random.normal(jax.random.key(0), (1, 6, 4))
    t = jax.random.normal(jax.random.key(1), (1, 6, 4))
    base = gram_loss(s, t)
    rn = gram_loss(s, t, remove_neg=True)
    rt = gram_loss(s, t, remove_only_teacher_neg=True)
    assert len({float(base), float(rn), float(rt)}) == 3  # all distinct
    with pytest.raises(ValueError):
        gram_loss(s, t, remove_neg=True, remove_only_teacher_neg=True)


def test_gram_default_config_allowed():
    # reference asserted remove_neg != remove_only_teacher_neg, crashing the
    # default False/False config (SURVEY.md §2.9.6); we accept it.
    s = jax.random.normal(jax.random.key(0), (1, 4, 4))
    assert np.isfinite(np.asarray(gram_loss(s, s + 0.1)))


# ---------------- zero-safe gradients ----------------

def test_l2_normalize_zero_gradient_finite():
    """Gradient of l2_normalize is finite at x == 0 (the x/(||x||+eps) form
    NaNs there; caught live: a fully-dropped-path sample's masked tokens are
    exactly the zero-init mask_token, which reaches the DINO head bottleneck
    as an all-zero vector)."""
    from dinov3_tpu.ops.common import l2_normalize

    def f(x):
        return jnp.sum(l2_normalize(x) ** 2)

    g = jax.grad(f)(jnp.zeros((4, 8)))
    assert bool(jnp.isfinite(g).all())
    # nonzero rows still normalize to unit length with correct gradient
    x = jax.random.normal(jax.random.key(0), (4, 8))
    y = l2_normalize(x)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(y), axis=-1), 1.0, atol=1e-5
    )
    g = jax.grad(f)(x)
    assert bool(jnp.isfinite(g).all())


def test_dino_head_zero_input_gradient_finite():
    """A zero feature row through DINOHead must produce finite grads for
    both head params and the input."""
    from dinov3_tpu.ops.dino_head import DINOHead

    head = DINOHead(out_dim=16, hidden_dim=8, bottleneck_dim=4, nlayers=3,
                    dtype=jnp.float32)
    x = jnp.zeros((2, 8), jnp.float32)
    params = head.init(jax.random.key(0), x)

    def loss(p, x):
        return jnp.sum(jax.nn.log_softmax(head.apply(p, x)) ** 2)

    gp, gx = jax.grad(loss, argnums=(0, 1))(params, x)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(gp))
    assert bool(jnp.isfinite(gx).all())


def test_koleo_zero_rows_gradient_finite():
    x = jnp.zeros((8, 16))
    g = jax.grad(lambda v: koleo_loss(v))(x)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))


def test_gram_token_mask_matches_subset():
    """tokens_used=masked via token_mask == dense gram on just the selected
    rows (gram.tokens_used, reference ssl_meta_arch.py:221-222)."""
    k = jax.random.key(0)
    s = jax.random.normal(k, (2, 6, 8))
    t = s + 0.05 * jax.random.normal(jax.random.fold_in(k, 1), (2, 6, 8))
    mask = jnp.zeros((2, 6), bool).at[:, :3].set(True)
    got = gram_loss(s, t, img_level=False, token_mask=mask)
    # manual: only the first 3 tokens of each image enter the gram
    sel_s = s[:, :3].reshape(-1, 8)
    sel_t = t[:, :3].reshape(-1, 8)
    import numpy as _np

    def gram(x):
        xn = _np.asarray(x) / _np.linalg.norm(
            _np.asarray(x), axis=-1, keepdims=True)
        return xn @ xn.T
    ref = ((gram(sel_s) - gram(sel_t)) ** 2).mean()
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)
