"""Host data pipeline: transforms, augmentations, collate, samplers,
loaders, datasets, multires combiner."""

import os

import numpy as np
import pytest
from PIL import Image

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import (
    CombineDataLoader,
    DataAugmentationDINO,
    DatasetWithEnumeratedTargets,
    EpochSampler,
    InfiniteSampler,
    ShardedInfiniteSampler,
    collate_crops,
    make_data_loader,
    make_dataset,
)
from dinov3_tpu.data.transforms import (
    ColorJitter,
    center_crop,
    make_classification_eval_transform,
    random_resized_crop,
    resize_shorter_side,
    to_normalized_array,
)


def _img(size=64, seed=0):
    rng = np.random.default_rng(seed)
    return Image.fromarray(
        rng.integers(0, 256, (size, size, 3), dtype=np.uint8)
    )


def _smol_cfg():
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "crops.global_crops_size=32", "crops.local_crops_size=16",
        "crops.local_crops_number=4", "student.patch_size=4",
    ])
    return cfg


# ------------------------------------------------------------- transforms


def test_random_resized_crop_shape_and_determinism():
    img = _img(100)
    a = random_resized_crop(np.random.default_rng(3), img, 32)
    b = random_resized_crop(np.random.default_rng(3), img, 32)
    assert a.size == (32, 32)
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_resize_center_crop_normalize():
    img = _img(80)
    out = center_crop(resize_shorter_side(img, 64), 48)
    assert out.size == (48, 48)
    arr = to_normalized_array(out)
    assert arr.shape == (48, 48, 3) and arr.dtype == np.float32
    t = make_classification_eval_transform(64, 48)
    arr2 = t(np.random.default_rng(0), img)
    assert np.allclose(arr, arr2)


def test_color_jitter_changes_image_but_is_deterministic():
    img = _img(32)
    jit = ColorJitter(0.4, 0.4, 0.2, 0.1)
    a = jit(np.random.default_rng(5), img)
    b = jit(np.random.default_rng(5), img)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(img))


# ---------------------------------------------------------- augmentations


def test_dino_augmentation_output_contract():
    aug = DataAugmentationDINO(
        global_crops_scale=(0.3, 1.0), local_crops_scale=(0.05, 0.3),
        local_crops_number=4, global_crops_size=32, local_crops_size=16,
    )
    out = aug(np.random.default_rng(0), _img(64))
    assert len(out["global_crops"]) == 2
    assert out["global_crops"][0].shape == (32, 32, 3)
    assert len(out["local_crops"]) == 4
    assert out["local_crops"][0].shape == (16, 16, 3)
    assert out["global_crops_teacher"] is out["global_crops"]
    assert "gram_teacher_crops" not in out


def test_dino_augmentation_gram_and_subset_modes():
    aug = DataAugmentationDINO(
        global_crops_scale=(0.3, 1.0), local_crops_scale=(0.05, 0.3),
        local_crops_number=4, global_crops_size=32, local_crops_size=16,
        gram_teacher_crops_size=24, gram_teacher_no_distortions=True,
        local_crops_subset_of_global_crops=True, patch_size=4,
        teacher_no_color_jitter=True,
    )
    out = aug(np.random.default_rng(0), _img(64))
    assert len(out["gram_teacher_crops"]) == 2
    assert out["gram_teacher_crops"][0].shape == (24, 24, 3)
    assert out["global_crops_teacher"] is not out["global_crops"]
    assert len(out["offsets"]) == 4
    for (rx, ry), crop in zip(out["offsets"], out["local_crops"]):
        assert rx % 4 == 0 and ry % 4 == 0
        assert crop.shape == (16, 16, 3)


# ---------------------------------------------------------------- collate


def test_collate_matches_meta_arch_contract():
    cfg = _smol_cfg()
    aug = DataAugmentationDINO(
        global_crops_scale=(0.3, 1.0), local_crops_scale=(0.05, 0.3),
        local_crops_number=4, global_crops_size=32, local_crops_size=16,
    )
    rng = np.random.default_rng(0)
    samples = [aug(np.random.default_rng(i), _img(64, i)) for i in range(3)]
    batch = collate_crops(
        samples, rng, patch_size=4, global_crops_size=32,
        mask_ratio_min_max=(0.1, 0.5), mask_probability=0.5,
    )
    T = (32 // 4) ** 2
    assert batch["global_crops"].shape == (6, 32, 32, 3)
    assert batch["local_crops"].shape == (12, 16, 16, 3)
    assert batch["masks"].shape == (6, T)
    C = batch["mask_indices"].shape[1]
    assert batch["mask_weights"].shape == (6, C)
    assert batch["mask_valid"].shape == (6, C)
    # crop-major: rows 0..2 are crop0 of each image
    assert np.allclose(batch["global_crops"][0], samples[0]["global_crops"][0])
    assert np.allclose(batch["global_crops"][3], samples[0]["global_crops"][1])
    # weights sum to 1 for each masked image
    has = batch["mask_valid"].any(axis=1)
    sums = batch["mask_weights"].sum(axis=1)
    assert np.allclose(sums[has], 1.0)


# ---------------------------------------------------------------- samplers


@pytest.mark.parametrize("cls", [EpochSampler, InfiniteSampler,
                                 ShardedInfiniteSampler])
def test_samplers_shard_disjoint_and_resume(cls):
    import itertools

    size, world = 40, 4
    streams = []
    for r in range(world):
        s = cls(size=size, rank=r, world_size=world, seed=7)
        streams.append(list(itertools.islice(iter(s), 30)))
    if cls is not InfiniteSampler:  # infinite draws i.i.d. — overlap allowed
        epoch_len = size if cls is EpochSampler else size // world
        for r, st in enumerate(streams):
            block = st[: epoch_len // (world if cls is EpochSampler else 1)]
            others = set().union(*(
                set(o[: len(block)]) for i, o in enumerate(streams) if i != r
            ))
            assert not (set(block) & others)
    # resume: advance(k) == skipping k draws
    s_full = cls(size=size, rank=1, world_size=world, seed=7)
    full = list(itertools.islice(iter(s_full), 20))
    s_adv = cls(size=size, rank=1, world_size=world, seed=7)
    k = 8 if cls is not EpochSampler else 8 * world
    s_adv.advance(k)
    resumed = list(itertools.islice(iter(s_adv), 12))
    assert resumed == full[8:]


# ------------------------------------------------------- loader + datasets


def test_synthetic_dataset_loader_end_to_end():
    cfg = _smol_cfg()
    from dinov3_tpu.data.pipeline import make_train_pipeline

    apply_dot_overrides(cfg, [
        "train.dataset_path=Synthetic:size=64:image_size=64",
        "train.num_workers=2",
    ])
    it = make_train_pipeline(cfg, global_batch_size=4)
    b1 = next(it)
    b2 = next(it)
    assert b1["global_crops"].shape == (8, 32, 32, 3)
    assert b1["local_crops"].shape == (16, 16, 16, 3)
    assert b1["global_crops"].dtype == np.float32
    assert not np.allclose(b1["global_crops"], b2["global_crops"])


def test_texture_dataset_generator(tmp_path):
    """Procedural texture classes (scripts/ablation_recipe.py data): 12
    structure-defined classes, color decorrelated from label, folder
    layout consumable by the ImageNet folder backend."""
    import numpy as np

    from dinov3_tpu.data.textures import (
        class_names,
        materialize_textures,
        render_texture,
    )

    assert len(class_names()) == 12
    rng = np.random.default_rng(0)
    # structure carries the class: band-limited spectra must land in
    # their own band (coarse vs fine blobs differ in spectral centroid)
    def centroid(img):
        g = img.mean(-1).astype(np.float64)
        g -= g.mean()
        spec = np.abs(np.fft.fft2(g))
        f = np.fft.fftfreq(g.shape[0]) * g.shape[0]
        fx, fy = np.meshgrid(f, f)
        r = np.hypot(fx, fy)
        return float((spec * r).sum() / spec.sum())

    c_coarse = np.mean([centroid(render_texture(rng, "blobs", "coarse"))
                        for _ in range(3)])
    c_fine = np.mean([centroid(render_texture(rng, "blobs", "fine"))
                      for _ in range(3)])
    assert c_fine > c_coarse + 2.0

    train_dir, val_dir = materialize_textures(
        str(tmp_path / "tex"), n_train_per_class=2, n_val_per_class=1,
        px=48)
    from dinov3_tpu.data.datasets import ImageFolder

    ds = ImageFolder(root=train_dir,
                     transform=lambda rng, im: to_normalized_array(im))
    assert len(ds) == 24
    img, target = ds[0]
    assert img.shape == (48, 48, 3)
    assert 0 <= target < 12
    # re-materialize is an idempotent no-op on a complete tree
    t2, _ = materialize_textures(str(tmp_path / "tex"),
                                 n_train_per_class=2, n_val_per_class=1,
                                 px=48)
    assert t2 == train_dir


def test_imagenet_folder_dataset(tmp_path):
    root = tmp_path / "in1k"
    for split in ("train", "val"):
        for wnid in ("n01440764", "n01443537"):
            d = root / split / wnid
            d.mkdir(parents=True)
            for i in range(3):
                _img(32, seed=i).save(d / f"{wnid}_{i}.JPEG")
    from dinov3_tpu.data.datasets import ImageNet

    ds = ImageNet(split="TRAIN", root=str(root),
                  transform=lambda rng, im: to_normalized_array(im))
    assert len(ds) == 6
    img, target = ds[0]
    assert img.shape == (32, 32, 3)
    assert target in (0, 1)
    assert ds.get_targets().tolist() == [0, 0, 0, 1, 1, 1]
    # index caching round-trip
    ds2 = ImageNet(split="TRAIN", root=str(root))
    assert len(ds2) == 6
    assert os.path.exists(root / "extra" / "entries-TRAIN.npy")


def test_imagenet22k_tar_dataset(tmp_path):
    import io
    import tarfile

    root = tmp_path / "in22k"
    root.mkdir()
    for wnid in ("n00001", "n00002"):
        with tarfile.open(root / f"{wnid}.tar", "w") as tf:
            for i in range(2):
                buf = io.BytesIO()
                _img(24, seed=i).save(buf, format="JPEG")
                data = buf.getvalue()
                info = tarfile.TarInfo(f"{wnid}_{i}.JPEG")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    from dinov3_tpu.data.datasets import ImageNet22k

    ds = ImageNet22k(root=str(root),
                     transform=lambda rng, im: to_normalized_array(im))
    assert len(ds) == 4
    img, target = ds[0]
    assert img.shape == (24, 24, 3)
    assert sorted(set(ds.get_targets().tolist())) == [0, 1]


def test_dataset_with_enumerated_targets():
    from dinov3_tpu.data.datasets import SyntheticImages

    base = SyntheticImages(size=5, image_size=8, n_classes=3)
    ds = DatasetWithEnumeratedTargets(base, pad_dataset=True, num_replicas=4)
    assert len(ds) == 8
    _, (idx, t) = ds[2]
    assert idx == 2 and t is not None
    _, (idx, _) = ds[6]
    assert idx == -1


def test_combine_dataloader_ratio_and_determinism():
    a = [{"src": "a", "i": i} for i in range(100)]
    b = [{"src": "b", "i": i} for i in range(100)]
    combined = CombineDataLoader([a, b], [0.75, 0.25], seed=3)
    got = [x["src"] for _, x in zip(range(80), iter(combined))]
    frac_a = got.count("a") / len(got)
    assert 0.55 < frac_a < 0.95
    combined2 = CombineDataLoader([a, b], [0.75, 0.25], seed=3)
    got2 = [x["src"] for _, x in zip(range(80), iter(combined2))]
    assert got == got2


def test_loader_worker_error_propagates():
    class Bad:
        def __len__(self):
            return 10

        def __getitem__(self, i):
            raise ValueError("boom")

    loader = make_data_loader(
        Bad(), batch_size=2, collate_fn=lambda s: s, num_workers=2,
    )
    with pytest.raises(ValueError, match="boom"):
        next(iter(loader))


def test_image_folder_dataset_and_backend(tmp_path):
    import numpy as np
    from PIL import Image

    from dinov3_tpu.data.datasets import ImageFolder
    from dinov3_tpu.data.loaders import make_dataset

    rng = np.random.default_rng(0)
    for cls in ("cats", "dogs"):
        (tmp_path / cls).mkdir()
        for i in range(3):
            Image.fromarray(
                rng.integers(0, 255, (32, 40, 3), dtype=np.uint8)
            ).save(tmp_path / cls / f"{i}.png")

    ds = ImageFolder(root=str(tmp_path))
    assert len(ds) == 6
    assert ds.classes == ["cats", "dogs"]
    img, target = ds[0]
    assert target == 0 and img.size == (40, 32)
    assert ds.get_targets().tolist() == [0, 0, 0, 1, 1, 1]

    # reachable through the dataset-string registry (data.backend=folder)
    ds2 = make_dataset(f"Folder:root={tmp_path}")
    assert len(ds2) == 6


def test_web_shards_dataset(tmp_path):
    import io
    import tarfile

    import numpy as np
    from PIL import Image

    from dinov3_tpu.data.loaders import make_dataset

    rng = np.random.default_rng(0)
    n_per_shard = 3
    for si in range(2):
        with tarfile.open(tmp_path / f"shard-{si:06d}.tar", "w") as tf:
            for i in range(n_per_shard):
                key = f"{si}_{i}"
                buf = io.BytesIO()
                Image.fromarray(
                    rng.integers(0, 255, (24, 24, 3), dtype=np.uint8)
                ).save(buf, format="PNG")
                data = buf.getvalue()
                info = tarfile.TarInfo(f"{key}.png")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                cls = str(si * 10 + i).encode()
                info = tarfile.TarInfo(f"{key}.cls")
                info.size = len(cls)
                tf.addfile(info, io.BytesIO(cls))

    ds = make_dataset(f"WebShards:root={tmp_path}")
    assert len(ds) == 6
    img, target = ds[0]
    assert img.size == (24, 24)
    assert sorted(ds.get_targets().tolist()) == [0, 1, 2, 10, 11, 12]
    # header index is cached next to the shards and reused
    assert (tmp_path / "shard-000000.tar.idx.npy").exists()
    ds2 = make_dataset(f"WebShards:root={tmp_path}")
    assert ds2.get_targets().tolist() == ds.get_targets().tolist()


def test_web_shards_val_split_requires_own_shards(tmp_path):
    import io
    import tarfile

    import numpy as np
    import pytest
    from PIL import Image

    from dinov3_tpu.data.datasets import WebShards

    rng = np.random.default_rng(0)

    def write_shard(path, n, label0):
        with tarfile.open(path, "w") as tf:
            for i in range(n):
                buf = io.BytesIO()
                Image.fromarray(
                    rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
                ).save(buf, format="PNG")
                data = buf.getvalue()
                info = tarfile.TarInfo(f"k{i}.png")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                cls = str(label0 + i).encode()
                info = tarfile.TarInfo(f"k{i}.cls")
                info.size = len(cls)
                tf.addfile(info, io.BytesIO(cls))

    write_shard(tmp_path / "shard-000000.tar", 3, 0)
    # VAL without its own shards must refuse (not silently serve TRAIN)
    with pytest.raises(FileNotFoundError):
        WebShards(root=str(tmp_path), split="VAL")
    # VAL with a split subdirectory works and is distinct
    (tmp_path / "val").mkdir()
    write_shard(tmp_path / "val" / "shard-000000.tar", 2, 100)
    val = WebShards(root=str(tmp_path), split="VAL")
    assert len(val) == 2
    assert sorted(val.get_targets().tolist()) == [100, 101]


def test_synthetic_cache_dataset_cycles():
    """train.cache_dataset pregenerates a pool and cycles it."""
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import SyntheticDataset

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.patch_size=4", "crops.global_crops_size=16",
        "crops.local_crops_size=8", "crops.local_crops_number=2",
        "train.cache_dataset=true",
    ])
    it = iter(SyntheticDataset(cfg, 2, seed=0))
    pool = SyntheticDataset.CACHE_POOL
    first = next(it)
    for _ in range(pool - 1):
        next(it)
    again = next(it)  # wrapped around
    np.testing.assert_array_equal(first["global_crops"], again["global_crops"])

    # default (no cache): consecutive batches differ
    apply_dot_overrides(cfg, ["train.cache_dataset=false"])
    it = iter(SyntheticDataset(cfg, 2, seed=0))
    a, b = next(it), next(it)
    assert not np.array_equal(a["global_crops"], b["global_crops"])
