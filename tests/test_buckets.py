"""Bucketed, overlap-scheduled collective engine
(train/fused_update.py make_bucketed_update + BucketPlan) vs the
per-leaf sharded oracle.

The bucketed engine is the default update path at data-parallel size > 1
(``optim.bucketed_collectives``); the per-leaf sharded schedule stays in
the tree as the bitwise oracle behind ``=false``. These tests pin:
- BucketPlan assembly invariants (single dtype/submodel/last-layer-group
  per bucket, deterministic order, padded offsets) and the bitwise
  round-trips through every packing direction (pack/unpack, the
  shard-interleaved bucket layout <-> per-leaf padded flat);
- multi-step equivalence of the bucketed engine against
  ``make_sharded_update`` with state feedback: the REDUCTION path is
  BITWISE — the shard-interleaved layout makes the coalesced
  reduce-scatter compute segment-for-segment the per-leaf sums, so the
  moments (mu/nu, every step) and the clip norms are bit-identical.
  The elementwise params/teacher outputs are pinned at the PR-5
  tolerances plus an explicit <= 8-ulp ceiling: XLA:CPU expands the
  shared ``optimization_barrier`` fusion cuts away pre-fusion, so the
  two programs' math kernels FMA-contract in different fusion contexts
  (~1-2 ulp observed); on backends that honor the barrier the math
  subgraphs compile identically;
- the explicit-collective schedule twin (the program
  scripts/cost_buckets.py commits the census of): same bar, and
  its compiled HLO carries exactly ONE reduce-scatter per bucket and ONE
  all-gather per bucket per output tree, all attributed to the
  ``bucket_pack``/``bucket_unpack`` scopes, with the per-class
  power-of-two size histogram populated;
- build_train_setup wiring: auto-on at dp > 1 (moments born as
  {bucket_name: flat} dicts), =false per-leaf fallback, the
  explicit-true conflicts (zero3 / fused off) raising;
- full-step bucketed-vs-per-leaf A/B dryrun and the cross-arm
  checkpoint round-trip (on-disk format stays per-leaf flat; the
  Checkpointer's bucket_plan adapter converts at the boundary) with
  resume determinism;
- the ``warn_bucket_padding`` guardrail (pad-fraction + straggler
  messages, silent clean case);
- the bucketed overlap twin (models/streaming.py
  ``bucketed_stream_scan``): under ``jax.grad`` the per-bucket forward
  all-gather transposes to a reduce-scatter INSIDE the backward while
  loop — the overlap placement ``utils.hlo_collective_placement``
  classifies;
- the COST_BUCKET_r13.json acceptance census: 357 -> <=16 update-phase
  reduce-scatters, 714 -> <=32 all-gathers at ViT-L dp=8, zero
  unattributed, >= 90% of collective bytes in >=64MiB buckets.
"""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
from dinov3_tpu.train import (
    build_multiplier_trees,
    make_bucket_plan,
    make_bucketed_update,
    make_bucketed_update_schedule,
    make_sharded_update,
)
from dinov3_tpu.train.fused_update import (
    bucketed_adam_zeros,
    flatten_update_leaf,
    sharded_adam_zeros,
)
from dinov3_tpu.train.optimizer import scheduled_adamw
from test_fused_update import (
    fake_params,
    grads_like,
    make_sched,
    smol_cfg,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _isolate_mesh_context():
    """build_train_setup registers its mesh in the process-global
    current-mesh registry; restore whatever was there so later test
    FILES (alphabetically after this one) don't inherit an 8-way data
    mesh their row/batch shapes can't divide."""
    from dinov3_tpu.parallel.context import get_current_mesh, set_current_mesh

    prev = get_current_mesh()
    yield
    set_current_mesh(prev)


@pytest.fixture(scope="module")
def mesh8(request):
    devs = jax.devices()
    assert len(devs) == 8
    return build_mesh(MeshSpec(data=8), devices=devs)


def small_plan(params=None, dp=8, target_bytes=256):
    """A plan over the smol fake tree with a tiny byte target so the
    greedy fill actually produces several buckets per group."""
    params = fake_params() if params is None else params
    _, _, ll = build_multiplier_trees(params, layerwise_decay=0.9)
    return params, make_bucket_plan(params, dp, is_last_layer=ll,
                                    target_bytes=target_bytes)


def bucketed_opt_init(params, sched, lm, wm, ll, plan):
    """Oracle-chain init with mu/nu swapped into the bucket layout —
    what build_train_setup's boxed init produces."""
    import flax.linen as nn

    s = scheduled_adamw(sched, lm, wm, ll).init(params)
    return s._replace(adam=s.adam._replace(
        mu=nn.meta.unbox(bucketed_adam_zeros(plan)),
        nu=nn.meta.unbox(bucketed_adam_zeros(plan)),
    ))


def sharded_opt_init(params, sched, lm, wm, ll, dp=8):
    import flax.linen as nn

    s = scheduled_adamw(sched, lm, wm, ll).init(params)
    return s._replace(adam=s.adam._replace(
        mu=nn.meta.unbox(sharded_adam_zeros(params, dp)),
        nu=nn.meta.unbox(sharded_adam_zeros(params, dp)),
    ))


def assert_trees_bitwise(a, b, what, limit=None):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(fa) == len(fb), f"{what}: leaf count {len(fa)} != {len(fb)}"
    if limit:
        fa, fb = fa[:limit], fb[:limit]
    for (pa, la), (_, lb) in zip(fa, fb):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: bitwise mismatch at {jax.tree_util.keystr(pa)}")


def assert_trees_ulp(a, b, what, max_ulp=8):
    """Elementwise pin for the cross-arm fp32 outputs: PR-5 tolerances
    AND an integer-ulp ceiling (the observed CPU FMA-contraction
    context drift is 1-2 ulp; 8 leaves margin without letting a real
    bug through)."""
    for (pa, la), (_, lb) in zip(
        jax.tree_util.tree_flatten_with_path(a)[0],
        jax.tree_util.tree_flatten_with_path(b)[0],
    ):
        la, lb = np.asarray(la), np.asarray(lb)
        np.testing.assert_allclose(
            la, lb, rtol=1e-6, atol=1e-7,
            err_msg=f"{what}: {jax.tree_util.keystr(pa)}")
        if la.dtype == np.float32:
            ulp = np.abs(la.view(np.int32).astype(np.int64)
                         - lb.view(np.int32).astype(np.int64))
            assert ulp.max(initial=0) <= max_ulp, (
                f"{what}: {jax.tree_util.keystr(pa)} drifted "
                f"{ulp.max()} ulp")


# ---------------- plan assembly + round-trips ----------------

def test_plan_grouping_invariants():
    """Every bucket is homogeneous in (submodel, dtype, last-layer
    group); member offsets tile the bucket exactly; the global order is
    deterministic (first member's tree position) and the names encode
    it."""
    params, plan = small_plan()
    n_leaves = len(jax.tree.leaves(params))
    assert plan.n_leaves == n_leaves
    assert sum(len(b.members) for b in plan.buckets) == n_leaves
    assert len(plan.buckets) >= 3  # tiny target forces a real partition
    seen = set()
    for b in plan.buckets:
        assert b.group in ("backbone", "dino_head")
        off = 0
        for m in b.members:
            assert m.index not in seen
            seen.add(m.index)
            assert m.offset == off
            assert m.padded % plan.dp == 0 and m.padded >= m.size
            off += m.padded
        assert off == b.size and b.size % plan.dp == 0
    # prototypes (the last-layer group) never share a bucket with the
    # rest of the head
    ll_buckets = [b for b in plan.buckets if b.is_last_layer]
    assert len(ll_buckets) >= 1
    assert all(b.group == "dino_head" for b in ll_buckets)
    assert all("prototypes" in m.path
               for b in ll_buckets for m in b.members)
    # deterministic order: names are the sorted traversal order
    assert list(plan.names) == sorted(plan.names)
    firsts = [b.members[0].index for b in plan.buckets]
    assert firsts == sorted(firsts)
    # rebuild -> identical plan
    _, plan2 = small_plan()
    assert plan2.names == plan.names
    assert [tuple(m.index for m in b.members) for b in plan2.buckets] == \
        [tuple(m.index for m in b.members) for b in plan.buckets]


def test_plan_pack_unpack_bitwise():
    """pack_tree -> unpack_tree and the per-leaf-flat <-> bucket
    conversions (the checkpoint boundary) round-trip bitwise, on both
    jax and numpy leaves."""
    params, plan = small_plan()
    key = jax.random.key(7)
    tree = grads_like(params, key)

    buckets = plan.pack_tree(tree)
    assert set(buckets) == set(plan.names)
    for b in plan.buckets:
        assert buckets[b.name].shape == (b.size,)
        assert buckets[b.name].dtype == b.dtype
    back = plan.unpack_tree(buckets, params)
    assert_trees_bitwise(tree, back, "pack/unpack")

    # shard-interleave layout: row k of the [dp, S/dp] view is the
    # member-by-member concat of each leaf's k-th flat shard
    flat_tree = jax.tree.map(
        lambda l: flatten_update_leaf(l, plan.dp), tree)
    b0 = plan.buckets[0]
    mat = np.asarray(buckets[b0.name]).reshape(plan.dp, -1)
    col = 0
    for m in b0.members:
        leaf = np.asarray(jax.tree.leaves(flat_tree)[m.index])
        w = m.padded // plan.dp
        assert np.array_equal(mat[:, col:col + w],
                              leaf.reshape(plan.dp, w))
        col += w

    # checkpoint boundary: bucket dict <-> per-leaf padded flat tree
    flat_back = plan.buckets_to_flat_tree(buckets)
    assert_trees_bitwise(flat_tree, flat_back, "buckets->flat")
    re_buckets = plan.flat_tree_to_buckets(flat_back)
    assert_trees_bitwise(buckets, re_buckets, "flat->buckets")
    # ... and numpy leaves (the local-npz checkpoint backend) too
    np_buckets = plan.flat_tree_to_buckets(
        jax.tree.map(np.asarray, flat_tree))
    assert_trees_bitwise(buckets, np_buckets, "np flat->buckets")

    # flat round-trip validates shapes
    bad = dict(jax.tree_util.tree_flatten_with_path(flat_tree)[0])
    with pytest.raises(ValueError):
        plan.flat_tree_to_buckets(
            jax.tree.map(lambda l: l[:-1], flat_tree))


# ---------------- engine bitwise equivalence ----------------

@pytest.mark.parametrize("clip", [3.0, 0.05, None])
def test_bucketed_matches_sharded(mesh8, clip):
    """6 steps with state feedback: the bucketed engine's REDUCTION
    path is BITWISE the per-leaf sharded engine's — mu/nu (through the
    lossless bucket <-> flat conversion) and the clip norms are
    bit-identical every step, because the shard-interleaved layout
    makes the coalesced reduce-scatter's segments exactly the per-leaf
    reduce-scatters'. The elementwise params/teacher outputs carry the
    PR-5 tolerance + ulp ceiling (module docstring: XLA:CPU drops the
    optimization_barrier fusion cut, so FMA contraction context may
    differ by 1-2 ulp between the compiled arms)."""
    sched = make_sched()
    params, plan = small_plan(target_bytes=512)
    lm, wm, ll = build_multiplier_trees(
        params, layerwise_decay=0.9, patch_embed_lr_mult=0.2,
        dino_head_wd_multiplier=0.5,
    )
    sharded = make_sharded_update(sched, lm, wm, ll, mesh8,
                                  clip_grad=clip, ema=True)
    bucketed = make_bucketed_update(sched, lm, wm, ll, mesh8, plan,
                                    clip_grad=clip, ema=True)
    momentum = jnp.asarray(0.95, jnp.float32)
    teacher = jax.tree.map(jnp.copy, params)
    s_s = sharded_opt_init(params, sched, lm, wm, ll)
    s_b = bucketed_opt_init(params, sched, lm, wm, ll, plan)

    with mesh8:
        s_step = jax.jit(lambda g, p, t, s: sharded(g, p, t, s, momentum))
        b_step = jax.jit(lambda g, p, t, s: bucketed(g, p, t, s, momentum))
        p_s = p_b = params
        t_s = t_b = teacher
        key = jax.random.key(0)
        for _ in range(6):
            key, k = jax.random.split(key)
            g = grads_like(params, k)
            p_s, t_s, s_s, n_s = s_step(g, p_s, t_s, s_s)
            p_b, t_b, s_b, n_b = b_step(g, p_b, t_b, s_b)
            # the reduction path: moments + clip norms BITWISE per step
            assert_trees_bitwise(
                s_s.adam.mu, plan.buckets_to_flat_tree(s_b.adam.mu), "mu")
            assert_trees_bitwise(
                s_s.adam.nu, plan.buckets_to_flat_tree(s_b.adam.nu), "nu")
            for k2 in n_s:
                assert float(n_s[k2]) == float(n_b[k2]), f"norm {k2}"

    assert_trees_ulp(p_s, p_b, "params")
    assert_trees_ulp(t_s, t_b, "teacher")
    assert int(s_b.count) == 6 and int(s_b.adam.count) == 6
    # the updates were non-trivial
    assert not np.array_equal(np.asarray(jax.tree.leaves(p_b)[0]),
                              np.asarray(jax.tree.leaves(params)[0]))


def test_bucketed_rejects_foreign_opt_state(mesh8):
    sched = make_sched()
    params, plan = small_plan()
    lm, wm, ll = build_multiplier_trees(params)
    bucketed = make_bucketed_update(sched, lm, wm, ll, mesh8, plan,
                                    clip_grad=3.0, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    s_leaf = sharded_opt_init(params, sched, lm, wm, ll)
    with mesh8, pytest.raises(TypeError, match="bucket"):
        bucketed(fake_params(), params, params, s_leaf, momentum)


# ---------------- explicit schedule twin: bitwise + census ----------------

def test_bucketed_schedule_bitwise_and_census(mesh8):
    """The explicit-collective bucketed schedule (ONE psum_scatter per
    bucket, ONE all_gather per bucket per output — the program
    COST_BUCKET_r13.json accounts) vs the per-leaf schedule twin, from
    the same [dp, *leaf] stacks of per-replica partials: moments and
    RS'd clip norms BITWISE every step (the interleaved bucket
    reduce-scatter computes the per-leaf twin's exact segments);
    params/teacher at the elementwise ulp ceiling. And the compiled
    HLO censuses to exactly n_buckets reduce-scatters and 2*n_buckets
    all-gathers, all attributed to bucket scopes with the size
    histogram populated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.train import make_sharded_update_schedule
    from dinov3_tpu.utils import hlo_collective_census

    sched = make_sched()
    params, plan = small_plan(target_bytes=512)
    lm, wm, ll = build_multiplier_trees(params, layerwise_decay=0.9)
    clip = 0.05  # engaged every step: the RS'd norms must match too
    perleaf = make_sharded_update_schedule(sched, lm, wm, ll, mesh8,
                                           clip_grad=clip, ema=True)
    schedule = make_bucketed_update_schedule(sched, lm, wm, ll, mesh8,
                                             plan, clip_grad=clip, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    teacher = jax.tree.map(jnp.copy, params)
    s_s = sharded_opt_init(params, sched, lm, wm, ll)
    s_b = bucketed_opt_init(params, sched, lm, wm, ll, plan)

    with mesh8:
        s_step = jax.jit(lambda gp, p, t, s: perleaf(gp, p, t, s, momentum))
        c_step = jax.jit(lambda gp, p, t, s: schedule(gp, p, t, s, momentum))
        p_s = p_c = params
        t_s = t_c = teacher
        key = jax.random.key(3)
        for _ in range(3):
            key, k1, _ = jax.random.split(key, 3)
            parts = jax.tree.map(
                lambda l: jax.random.normal(
                    jax.random.fold_in(k1, l.size), (8,) + l.shape, l.dtype),
                params)
            p_s, t_s, s_s, norms_s = s_step(parts, p_s, t_s, s_s)
            p_c, t_c, s_b, norms_c = c_step(parts, p_c, t_c, s_b)
            assert_trees_bitwise(
                s_s.adam.mu, plan.buckets_to_flat_tree(s_b.adam.mu),
                "schedule mu")
            assert_trees_bitwise(
                s_s.adam.nu, plan.buckets_to_flat_tree(s_b.adam.nu),
                "schedule nu")
            for k in norms_s:
                assert float(norms_s[k]) == float(norms_c[k]), (
                    f"clip norm {k}")

    # ulp ceiling is looser here than the engine pair's: the drift is
    # on near-zero elements (abs diff ~1e-7) where the integer-ulp
    # metric inflates; the allclose inside still binds tightly
    assert_trees_ulp(p_s, p_c, "schedule params", max_ulp=64)
    assert_trees_ulp(t_s, t_c, "schedule teacher", max_ulp=64)

    # census of the EXACT explicit twin, compiled with the training
    # shardings (stacked partials + bucket moments over the data axes)
    rep = NamedSharding(mesh8, P())
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh8.shape)
    stacks = NamedSharding(mesh8, P(axes))
    gstack = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((8,) + l.shape, l.dtype), params)
    abs_p = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)
    abs_s = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), s_b)
    rep_tree = jax.tree.map(lambda _: rep, abs_p)
    opt_sh = jax.tree.map(
        lambda l: rep if l.ndim == 0 else stacks, abs_s)
    compiled = jax.jit(
        lambda gp, p, t, s: schedule(gp, p, t, s, momentum)[:3],
        in_shardings=(jax.tree.map(lambda _: stacks, gstack),
                      rep_tree, rep_tree, opt_sh),
        out_shardings=(rep_tree, rep_tree, opt_sh),
    ).lower(gstack, abs_p, abs_p, abs_s).compile()
    census = hlo_collective_census(compiled.as_text())
    n = len(plan.buckets)
    assert census["unattributed"] == 0
    rs = census["by_class"].get("reduce_scatter", {"ops": 0})
    ag = census["by_class"].get("all_gather", {"ops": 0})
    assert rs["ops"] == n, (n, census["by_class"])
    assert ag["ops"] == 2 * n, (n, census["by_class"])  # student + teacher
    # attribution: every bucket collective under a bucket_* scope
    bucket_scopes = {k: v for k, v in census["by_scope"].items()
                     if k.startswith("bucket")}
    assert sum(v["ops"] for v in bucket_scopes.values()) >= 3 * n
    # satellite: the per-class power-of-two size histogram is populated
    for cls in (rs, ag):
        hist = cls["size_histogram"]
        assert hist and all("floor_bytes" in b for b in hist.values())
        assert sum(b["ops"] for b in hist.values()) == cls["ops"]
        assert sum(b["bytes"] for b in hist.values()) == cls["bytes"]


# ---------------- setup wiring ----------------

def _setup(extra, batch_size, eight_devices):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = smol_cfg(["parallel.zero3=false"] + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=eight_devices), batch


def test_setup_born_bucketed_and_toggles(eight_devices):
    """auto-on at dp > 1: moments born as bucket dicts (superseding the
    per-leaf sharded arm); =false restores the per-leaf oracle; explicit
    true + zero3 composes (the unified gather-bucket arm) while the
    remaining non-zero3 conflicts still raise."""
    setup, _ = _setup(["parallel.data=-1"], 8, eight_devices)
    assert setup.bucketed and setup.bucket_plan is not None
    assert not setup.sharded_update  # bucketed supersedes per-leaf
    assert setup.fused_update is not None
    mu = setup.state.opt_state.adam.mu
    assert isinstance(mu, dict)
    assert sorted(mu) == sorted(setup.bucket_plan.names)
    for b in setup.bucket_plan.buckets:
        leaf = mu[b.name]
        assert leaf.ndim == 1 and leaf.shape == (b.size,)

    # =false: the per-leaf sharded oracle arm
    setup_off, _ = _setup(["parallel.data=-1",
                           "optim.bucketed_collectives=false"], 8,
                          eight_devices)
    assert not setup_off.bucketed and setup_off.bucket_plan is None
    assert setup_off.sharded_update
    assert all(l.ndim == 1 for l in
               jax.tree.leaves(setup_off.state.opt_state.adam.mu))

    # explicit true + zero3 selects the unified gather-bucket arm (the
    # flat bucketed update stays out of the way: zero3 owns the update)
    setup_z3, _ = _setup(["parallel.data=-1", "parallel.zero3=true",
                          "optim.bucketed_collectives=true"], 8,
                         eight_devices)
    assert setup_z3.zero3 and setup_z3.zero3_buckets
    assert setup_z3.zero3_bucket_plan is not None
    assert not setup_z3.bucketed and setup_z3.bucket_plan is None
    # explicit true + fused off likewise
    with pytest.raises(ValueError, match="bucketed_collectives"):
        _setup(["parallel.data=-1", "optim.fused_update=false",
                "optim.bucketed_collectives=true"], 8, eight_devices)


def test_full_step_bucketed_vs_perleaf(eight_devices):
    """Dryrun A/B at dp=8: 2 full steps from the same init, the
    bucketed arm matches the per-leaf oracle at the PR-5 dryrun
    tolerances (losses to 1e-5, params/moments to 5e-6; the full step's
    forward/backward fuses differently around the two update engines,
    so the ulp-exact pins live in the engine/schedule tests above)."""
    from dinov3_tpu.train import put_batch

    results = {}
    for flag in ("auto", "false"):
        setup, batch = _setup(
            ["parallel.data=-1", f"optim.bucketed_collectives={flag}"], 8,
            eight_devices)
        assert setup.bucketed == (flag == "auto")
        d = put_batch(batch, setup.batch_shardings)
        state = setup.state
        losses = []
        for i in range(2):
            state, m = setup.step_fn(state, d, setup.scalars(i),
                                     jax.random.key(0))
            losses.append(float(m["total_loss"]))
        results[flag] = (setup, state, losses)

    setup_b, st_b, loss_b = results["auto"]
    _, st_p, loss_p = results["false"]
    for a, b in zip(loss_b, loss_p):
        assert a == pytest.approx(b, rel=1e-5)
    for (pa, la), (_, lb) in zip(
        jax.tree_util.tree_flatten_with_path(st_p.params)[0][:64],
        jax.tree_util.tree_flatten_with_path(st_b.params)[0][:64],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=5e-6, atol=1e-6,
            err_msg=f"dryrun params {jax.tree_util.keystr(pa)}")
    mu_b = setup_b.bucket_plan.buckets_to_flat_tree(st_b.opt_state.adam.mu)
    for (pa, la), (_, lb) in zip(
        jax.tree_util.tree_flatten_with_path(st_p.opt_state.adam.mu)[0],
        jax.tree_util.tree_flatten_with_path(mu_b)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=5e-6, atol=1e-6,
            err_msg=f"dryrun mu {jax.tree_util.keystr(pa)}")


# ---------------- checkpoint round-trip + resume determinism ----------------

def test_checkpoint_cross_arm_roundtrip(tmp_path, eight_devices):
    """bucketed -> per-leaf -> bucketed checkpoint round-trip: on disk
    the moments are ALWAYS per-leaf flat (the Checkpointer's
    bucket_plan adapter converts at the boundary — pure index
    permutations, bitwise lossless), and the resumed run is
    deterministic."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch

    setup_bk, batch = _setup(["parallel.data=-1"], 8, eight_devices)
    assert setup_bk.bucketed
    d = put_batch(batch, setup_bk.batch_shardings)
    state1, _ = setup_bk.step_fn(setup_bk.state, d, setup_bk.scalars(0),
                                 jax.random.key(0))

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False,
                      bucket_plan=setup_bk.bucket_plan)
    ck.save(1, state1)
    ck.wait_until_finished()

    # restore into the per-leaf sharded arm: a plain Checkpointer (no
    # plan) reads the same checkpoint — the disk format IS per-leaf
    setup_pl, _ = _setup(["parallel.data=-1",
                          "optim.bucketed_collectives=false"], 8,
                         eight_devices)
    ck_plain = Checkpointer(str(tmp_path / "ck"), async_save=False)
    pl_state = ck_plain.restore(setup_pl.state, 1)
    assert_trees_bitwise(
        pl_state.opt_state.adam.mu,
        setup_bk.bucket_plan.buckets_to_flat_tree(state1.opt_state.adam.mu),
        "disk mu is the per-leaf flat form")

    # ... and back: the per-leaf arm's save restores bitwise into the
    # bucketed arm through the adapter
    ck_plain.save(2, pl_state)
    ck_plain.wait_until_finished()
    back = ck.restore(setup_bk.state, 2)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(state1.opt_state)[0],
        jax.tree_util.tree_flatten_with_path(back.opt_state)[0],
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"round-trip changed {jax.tree_util.keystr(path)}")

    # resume determinism: the next step from the round-tripped state is
    # the next step from the original state
    s_orig, m_orig = setup_bk.step_fn(state1, d, setup_bk.scalars(1),
                                      jax.random.key(0))
    s_back, m_back = setup_bk.step_fn(back, d, setup_bk.scalars(1),
                                      jax.random.key(0))
    assert float(m_orig["total_loss"]) == float(m_back["total_loss"])
    assert_trees_bitwise(s_orig.params, s_back.params, "resume", limit=32)

    # the per-leaf arm also RUNS from the adapted state
    d_pl = put_batch(batch, setup_pl.batch_shardings)
    s_pl, m_pl = setup_pl.step_fn(pl_state, d_pl, setup_pl.scalars(1),
                                  jax.random.key(0))
    assert np.isfinite(float(m_pl["total_loss"]))
    assert int(s_pl.step) == 2


# ---------------- guardrail ----------------

def test_bucket_padding_guardrail(recwarn):
    from dinov3_tpu.configs.config import warn_bucket_padding

    def row(name, elems, pad, nbytes):
        return {"name": name, "group": "backbone", "dtype": "f32",
                "is_last_layer": False, "n_leaves": 1, "elems": elems,
                "pad_elems": pad, "bytes": nbytes}

    # clean plan: equal buckets, negligible padding -> silent
    clean = [row(f"b{i:03d}", 10 ** 6, 8, 4 * 10 ** 6) for i in range(4)]
    assert warn_bucket_padding(clean, 4 * 10 ** 6) == []
    assert len(recwarn.list) == 0

    # pad-fraction pathology: >5% zeros in the coalesced payload
    msgs = warn_bucket_padding(
        [row("b000_backbone", 100, 20, 480)], 4 * 10 ** 6)
    assert len(msgs) == 1 and "bucket flat axis [b000_backbone]" in msgs[0]

    # straggler pathology: one bucket under 1/8 the median
    frag = [row("b000", 10 ** 6, 0, 4 * 10 ** 6),
            row("b001", 10 ** 6, 0, 4 * 10 ** 6),
            row("b002_tail", 10 ** 4, 0, 4 * 10 ** 4)]
    msgs = warn_bucket_padding(frag, 4 * 10 ** 6)
    assert len(msgs) == 1 and "bucket size axis [b002_tail]" in msgs[0]
    w = [x for x in recwarn.list if "bucket" in str(x.message)]
    assert len(w) == 2  # one per pathology above

    # a REAL smol plan at the default target is clean (one bucket per
    # group -> no straggler comparison, padding under threshold is the
    # small-tree exemption the setup path relies on)


def test_setup_guardrail_fires_on_fragmented_plan(eight_devices, recwarn):
    """The guardrail is wired into build_train_setup: a pathologically
    small optim.bucket_mb fragments the smol tree into stragglers and
    the warning surfaces at setup build."""
    _setup(["parallel.data=-1", "optim.bucket_mb=1"], 8, eight_devices)
    # smol tree at 1MiB target: single-bucket groups of wildly unequal
    # size -> the straggler/pad guardrail may or may not fire, but the
    # call must not raise; force the fragmenting case directly instead
    from dinov3_tpu.configs.config import warn_bucket_padding
    from dinov3_tpu.train import make_bucket_plan

    params = {"backbone": {
        "big": jnp.zeros((4096,)), "tiny_a": jnp.zeros((3,)),
        "tiny_b": jnp.zeros((5,))}}
    plan = make_bucket_plan(params, 8, target_bytes=4096 * 4)
    msgs = warn_bucket_padding(plan.padding_stats(), plan.target_bytes)
    assert isinstance(msgs, list)


# ---------------- overlap twin ----------------

def test_overlap_twin_placement(mesh8):
    """grad of the bucketed stream scan: the per-bucket param
    all-gather rides the FORWARD while loop (plus the at-barrier
    priming gather of the double buffer); its transpose — the coalesced
    grad reduce-scatter — lands INSIDE the backward while loop. This is
    the overlap-placement evidence COST_BUCKET_r13.json commits at
    ViT-L scale."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import (
        bucketed_stream_scan,
        pack_stream_buckets,
    )
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.utils import hlo_collective_census

    n_blocks, n_buckets, dp = 8, 4, 8
    stack = {
        "attn": {"qkv": {"kernel": jnp.zeros((n_blocks, 16, 48),
                                             jnp.bfloat16)},
                 "proj": {"kernel": jnp.zeros((n_blocks, 16, 16),
                                              jnp.bfloat16)}},
        "mlp": {"fc1": {"kernel": jnp.zeros((n_blocks, 16, 64),
                                            jnp.bfloat16)},
                "fc2": {"kernel": jnp.zeros((n_blocks, 64, 16),
                                            jnp.bfloat16)}},
    }
    shards = jax.eval_shape(
        lambda s: pack_stream_buckets(s, n_buckets, dp), stack)
    x = jax.ShapeDtypeStruct((dp * 4,), jnp.float32)
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh8.shape)

    def loss(shards, x):
        return jnp.sum(bucketed_stream_scan(
            shards, x, mesh=mesh8, prefetch=True))

    compiled = jax.jit(
        jax.grad(loss),
        in_shardings=(NamedSharding(mesh8, P(None, axes)),
                      NamedSharding(mesh8, P())),
        out_shardings=NamedSharding(mesh8, P(None, axes)),
    ).lower(shards, x).compile()
    census = hlo_collective_census(compiled.as_text())
    assert census["unattributed"] == 0
    ag = census["by_class"]["all_gather"]["by_placement"]
    rs = census["by_class"]["reduce_scatter"]["by_placement"]
    assert ag.get("in-forward-loop", {"ops": 0})["ops"] >= 1, census
    assert rs.get("in-backward-loop", {"ops": 0})["ops"] >= 1, census
    # the gathers ride the bucket scopes of the double buffer
    scopes = set(census["by_scope"])
    assert any(s.startswith("bucket") for s in scopes), scopes


def test_pack_stream_buckets_shape_and_divisibility():
    from dinov3_tpu.models.streaming import pack_stream_buckets

    stack = {"attn": {"qkv": {"kernel": jnp.ones((8, 4, 12),
                                                 jnp.bfloat16)}},
             "mlp": {"fc1": {"kernel": jnp.ones((8, 4, 16),
                                                jnp.bfloat16)}}}
    out = pack_stream_buckets(stack, 4, 8)
    assert out.shape[0] == 4 and out.shape[1] % 8 == 0
    # equal buckets: every bucket carries g = n_blocks/n_buckets block
    # slices of every streamable leaf
    assert out.shape[1] == (2 * (4 * 12) + 2 * (4 * 16))
    with pytest.raises(ValueError, match="must divide"):
        pack_stream_buckets(stack, 3, 8)


# ---------------- committed acceptance census ----------------

def test_cost_bucket_r13_acceptance():
    """The committed COST_BUCKET_r13.json (ViT-L dp=8, compile-only on
    8 simulated devices): update-phase RS 357 -> <= 16 and AG
    714 -> <= 32, zero unattributed in both twins, >= 90% of collective
    bytes in >= 64MiB buckets, and the overlap twin's grad RS placed
    in the backward loop."""
    rec = json.loads((REPO / "COST_BUCKET_r13.json").read_text())
    assert rec["dp"] == 8 and rec["arch"] == "vit_large"
    rs, ag = rec["reduce_scatter_ops"], rec["all_gather_ops"]
    assert rs["per_leaf"] >= 300 and ag["per_leaf"] >= 600
    assert rs["bucketed"] <= 16 and ag["bucketed"] <= 32

    up = rec["update_phase"]
    for arm in ("per_leaf", "bucketed"):
        assert up["collective_census"][arm]["unattributed"] == 0
    assert up["big_bin_fraction"]["bucketed"] >= 0.90
    assert up["plan"]["n_buckets"] == rs["bucketed"]
    assert up["n_param_leaves"] == rs["per_leaf"]

    ot = rec["overlap_twin"]
    oc = ot["collective_census"]
    assert oc["unattributed"] == 0
    rs_pl = oc["by_class"]["reduce_scatter"]["by_placement"]
    ag_pl = oc["by_class"]["all_gather"]["by_placement"]
    assert rs_pl.get("in-backward-loop", {"ops": 0})["ops"] >= 1
    assert ag_pl.get("in-forward-loop", {"ops": 0})["ops"] >= 1
