"""Serve-backed multi-student distillation (ROADMAP item 2): the packed
teacher engine's patch-feature plane, the precomputed-targets loss arm,
the content-addressed fan-out cache, and the one-forward-per-image
dedup across co-hosted student subgroups.

Pins:

- packed patch extraction: the ONE compiled packed forward's per-token
  features match the per-image oracle on ragged traffic (compile count
  stays 1), and the default CLS+pool path keeps a ZERO-width patch
  plane (same donated ring pytree, no patch HBM);
- the precomputed-targets arm of ``get_teacher_output`` is BITWISE
  equal to the in-step oracle when fed the oracle's own features —
  targets AND center state — because both arms share
  ``teacher_targets_from_features`` and the f32 batch planes
  round-trip the bf16 compute values exactly;
- cache fingerprint audit: int8 and bf16 serving trees of the same
  checkpoint never cross-serve a patch-plane entry, and a hit replays
  the SAME frozen buffers a miss stored;
- TeacherServer dedup: within-batch duplicates forward once, epoch
  replays hit the cache with bitwise-equal planes, and TWO co-hosted
  student subgroups sharing one teacher get ONE TeacherServer — one
  teacher evaluation per unique image, k students or not
  (COST_DISTILL_r22.json prices the same invariants on-chip).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.serve import (
    OracleServeEngine,
    PackedServeEngine,
    cast_serving_tree,
    load_serving_model,
    serve_layout_from_cfg,
)
from dinov3_tpu.serve.cache import FeatureCache, weights_fingerprint
from dinov3_tpu.train.distillation import (
    TeacherServer,
    teacher_feature_example,
)
from dinov3_tpu.train.multidistillation import (
    _SHARED_TEACHERS,
    shared_teacher_server,
)

SMOL = [
    "student.patch_size=4", "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
]

SERVE_SMOL = SMOL + [
    "student.arch=vit_test",
    "serve.min_px=8", "serve.max_px=24", "serve.rows=3",
    "serve.row_tokens=40", "serve.max_segments_per_row=6",
]


def _teacher_yaml(tmp_path, hidden=48):
    recipe = {
        "student": {"arch": "vit_test_big", "patch_size": 4,
                    "drop_path_rate": 0.0},
        "dino": {"head_n_prototypes": 64, "head_hidden_dim": hidden,
                 "head_bottleneck_dim": 16},
        "ibot": {"head_n_prototypes": 64, "head_hidden_dim": hidden,
                 "head_bottleneck_dim": 16},
        "crops": {"global_crops_size": 16, "local_crops_size": 8,
                  "local_crops_number": 2},
        "optim": {"scaling_rule": "none"},
    }
    path = tmp_path / "teacher.yaml"
    path.write_text(yaml.safe_dump(recipe))
    return str(path)


def _distill_cfg(tmp_path, source="in_step"):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "student.arch=vit_test",
        "distillation.enabled=true",
        f"distillation.full_cfg_path={_teacher_yaml(tmp_path)}",
        f"distillation.teacher_source={source}",
    ])
    return cfg


@pytest.fixture(scope="module")
def tiny_serve():
    """One vit_test serving model + bf16 params + layout."""
    import flax.linen as nn

    from dinov3_tpu.models import build_backbone

    cfg = get_default_config()
    apply_dot_overrides(cfg, SERVE_SMOL)
    model = build_backbone(cfg, teacher=True)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    params = cast_serving_tree(params)
    return cfg, model, params, serve_layout_from_cfg(cfg)


# ------------------- packed patch-feature extraction -------------------

def test_packed_patch_features_match_oracle_single_compile(tiny_serve):
    """Ragged traffic: packed per-token features match the per-image
    oracle's, CLS unchanged, ONE packed compile."""
    cfg, model, params, layout = tiny_serve
    rng = np.random.default_rng(2)
    eng = PackedServeEngine(model, params, layout, warn=False,
                            patch_features=True)
    ora = OracleServeEngine(model, params, layout, mode="per_image",
                            patch_features=True)
    sizes = [(16, 16), (8, 8), (24, 16), (8, 12), (16, 16)]
    images = [rng.standard_normal((h, w, 3)).astype(np.float32)
              for h, w in sizes]
    for e in (eng, ora):
        for i, im in enumerate(images):
            e.submit(im, request_id=i)
    packed = []
    while eng.queue_len:
        packed.extend(eng.flush())
    oracle = {r.request_id: r for r in ora.flush()}
    assert len(packed) == len(images)
    for r in packed:
        o = oracle[r.request_id]
        assert r.patch_tokens is not None
        assert r.patch_tokens.shape == (o.n_patches, model.embed_dim)
        np.testing.assert_allclose(
            r.patch_tokens, o.patch_tokens, atol=1e-5,
            err_msg=f"patch tokens, request {r.request_id}")
        np.testing.assert_allclose(
            r.cls_feature, o.cls_feature, atol=1e-5,
            err_msg=f"cls, request {r.request_id}")
    assert eng.compile_count == 1


def test_patch_plane_zero_width_when_off(tiny_serve):
    """The default CLS+pool engine allocates a ZERO-token patch plane —
    same donated ring pytree structure, no patch HBM — and its
    responses carry patch_tokens=None."""
    cfg, model, params, layout = tiny_serve
    eng = PackedServeEngine(model, params, layout, warn=False)
    assert eng._ring.patch.shape[2] == 0
    on = PackedServeEngine(model, params, layout, warn=False,
                           patch_features=True)
    assert on._ring.patch.shape[2] == layout.row_tokens
    # identical pytree STRUCTURE (donation contract) across both arms
    assert (jax.tree_util.tree_structure(eng._ring)
            == jax.tree_util.tree_structure(on._ring))
    eng.submit(np.zeros((8, 8, 3), np.float32), request_id=0)
    (r,) = eng.flush()
    assert r.patch_tokens is None


# ------------------- precomputed-targets loss arm -------------------

def test_precomputed_targets_bitwise_vs_in_step_oracle(tmp_path):
    """Feeding the oracle's own backbone features through the serve arm
    reproduces the in-step teacher targets AND center state bitwise:
    both arms share ``teacher_targets_from_features``, and f32 plane
    storage round-trips the bf16 compute values exactly."""
    from dinov3_tpu.train import build_train_setup

    cfg = _distill_cfg(tmp_path)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    meta = setup.meta
    assert meta.teacher_source == "in_step"
    frozen = setup.state.params["teacher"]
    state0 = meta.init_state()
    temp = 0.05

    oracle_out, oracle_state = meta.get_teacher_output(
        frozen, batch, temp, state0)

    cls, patches = meta.teacher_backbone_features(frozen, batch)
    sbatch = dict(batch)
    sbatch["teacher_cls"] = jnp.asarray(np.asarray(cls, np.float32))
    sbatch["teacher_patches"] = jnp.asarray(np.asarray(patches, np.float32))
    meta.teacher_source = "serve"
    try:
        serve_out, serve_state = meta.get_teacher_output(
            frozen, sbatch, temp, state0)
        # missing planes is a hard error, not a silent oracle fallback
        with pytest.raises(ValueError, match="teacher_cls"):
            meta.get_teacher_output(frozen, batch, temp, state0)
    finally:
        meta.teacher_source = "in_step"

    for name, a, b in (("targets", oracle_out, serve_out),
                       ("state", oracle_state, serve_state)):
        la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y)), name


def test_teacher_feature_example_shapes(tmp_path):
    """The trace-batch planes match what TeacherServer.annotate emits:
    teacher embed dim x student-run patch grid."""
    cfg = _distill_cfg(tmp_path)
    ex = teacher_feature_example(cfg, 6)
    assert ex["teacher_cls"].shape == (6, 96)         # vit_test_big dim
    assert ex["teacher_patches"].shape == (6, 16, 96)  # (16/4)^2 tokens
    assert all(v.dtype == np.float32 for v in ex.values())


def test_setup_rejects_serve_source_without_planes(tmp_path):
    """teacher_source=serve with an example batch missing the planes
    fails at setup time, not at step-trace time."""
    from dinov3_tpu.train import build_train_setup

    cfg = _distill_cfg(tmp_path, source="serve")
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    with pytest.raises(ValueError, match="teacher_cls"):
        build_train_setup(cfg, batch)


# ------------------- cache fingerprint audit -------------------

def test_patch_plane_cache_never_cross_serves_quant_trees(tiny_serve):
    """int8 and bf16 serving trees of the SAME checkpoint have distinct
    fingerprints; a patch-plane entry stored under one is a MISS under
    the other, and a hit replays the SAME frozen buffers."""
    from dinov3_tpu.serve.quant import quantize_serving_tree

    _, _, params, _ = tiny_serve
    f_bf16 = weights_fingerprint(params)
    f_int8 = weights_fingerprint(quantize_serving_tree(params))
    assert f_bf16 != f_int8

    rng = np.random.default_rng(5)
    img = rng.standard_normal((16, 16, 3)).astype(np.float32)
    cache = FeatureCache(capacity=4)
    patch = rng.standard_normal((16, 8)).astype(np.float32)
    cache.put(cache.key(img, f_bf16),
              (np.zeros(8, np.float32), np.zeros(8, np.float32), 16, patch))
    assert cache.get(cache.key(img, f_int8)) is None
    hit = cache.get(cache.key(img, f_bf16))
    assert hit is not None and len(hit) == 4
    # the hit IS the stored plane (bitwise by construction), frozen
    assert np.array_equal(hit[3], patch)
    assert not hit[3].flags.writeable


def test_bench_distill_summary_block():
    """bench.py's "distill" record block: arm/teacher_source/embed dim,
    the distill_fanout scope slice of the census, and any process-level
    TeacherServer counters."""
    import bench

    class _Meta:
        distillation = True
        teacher_source = "serve"
        teacher_embed_dim = 96

    class _Setup:
        meta = _Meta()

    _SHARED_TEACHERS.clear()
    census = {"by_scope": {"distill_fanout": {"ops": 2},
                           "zero3_stream": {"ops": 9}}}
    out = bench._distill_summary(_Setup(), census)
    assert out["arm"] is True
    assert out["teacher_source"] == "serve"
    assert out["teacher_embed_dim"] == 96
    assert out["collectives_by_scope"] == {"distill_fanout": {"ops": 2}}
    assert "teacher_servers" not in out
    # non-distilling bench: arm off, no teacher dim
    class _Plain:
        meta = None
    plain = bench._distill_summary(_Plain(), None)
    assert plain["arm"] is False and plain["teacher_embed_dim"] is None


# ------------------- TeacherServer fan-out dedup -------------------

@pytest.fixture(scope="module")
def teacher_server_env(tmp_path_factory):
    """One distillation cfg + frozen teacher params + its TeacherServer
    (compiled once for the module — engine builds are the slow part)."""
    import flax.linen as nn

    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.train.distillation import resolve_distillation_cfg

    tmp = tmp_path_factory.mktemp("distill_serve")
    cfg = _distill_cfg(tmp, source="serve")
    teacher_cfg = resolve_distillation_cfg(cfg)
    tmodel = build_backbone(teacher_cfg, teacher=True)
    tparams = nn.meta.unbox(
        jax.jit(tmodel.init)(jax.random.key(1), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    srv = TeacherServer(cfg, teacher_params=tparams, warn=False)
    return cfg, tparams, srv


def test_teacher_server_dedups_and_replays_bitwise(teacher_server_env):
    cfg, _, srv = teacher_server_env
    base_fwd = srv.teacher_forwards
    rng = np.random.default_rng(7)
    g = rng.standard_normal((4, 16, 16, 3)).astype(np.float32)
    ann = srv.annotate({"global_crops": g})
    assert ann["teacher_cls"].shape == (4, srv.engine.model.embed_dim)
    assert ann["teacher_patches"].shape[1] == srv.patch_grid ** 2
    assert srv.teacher_forwards - base_fwd == 4
    # epoch replay: zero new forwards, bitwise-equal planes
    ann2 = srv.annotate({"global_crops": g})
    assert srv.teacher_forwards - base_fwd == 4
    assert np.array_equal(ann["teacher_cls"], ann2["teacher_cls"])
    assert np.array_equal(ann["teacher_patches"], ann2["teacher_patches"])
    # within-batch duplicates forward once (fresh images, repeated 2x)
    fresh = rng.standard_normal((2, 16, 16, 3)).astype(np.float32)
    before = srv.teacher_forwards
    srv.annotate({"global_crops": np.concatenate([fresh, fresh], axis=0)})
    assert srv.teacher_forwards - before == 2
    # the compile pin survives all of it
    assert srv.engine.compile_count == 1
    s = srv.stats()
    assert s["teacher_forwards"] < s["requests"]


def test_two_subgroups_share_one_teacher_server(teacher_server_env,
                                                tmp_path):
    """The two-subgroup dryrun: two student configs distilling from the
    SAME teacher resolve to the SAME process-level TeacherServer, so k
    students pay ONE teacher evaluation per unique image."""
    cfg, tparams, _ = teacher_server_env
    _SHARED_TEACHERS.clear()
    try:
        a = shared_teacher_server(cfg, teacher_params=tparams, warn=False)
        # subgroup B: different student arch, same teacher
        cfg_b = get_default_config()
        apply_dot_overrides(cfg_b, SMOL + [
            "student.arch=vit_test_big",
            "dino.head_hidden_dim=48", "ibot.head_hidden_dim=48",
            "distillation.enabled=true",
            f"distillation.full_cfg_path={cfg.distillation.full_cfg_path}",
            "distillation.teacher_source=serve",
        ])
        b = shared_teacher_server(cfg_b, teacher_params=tparams, warn=False)
        assert a is b
        rng = np.random.default_rng(11)
        g = rng.standard_normal((3, 16, 16, 3)).astype(np.float32)
        base = a.teacher_forwards
        a.annotate({"global_crops": g})    # subgroup A's pass
        b.annotate({"global_crops": g})    # subgroup B: all cache hits
        assert a.teacher_forwards - base == 3
        assert a.engine.compile_count == 1
        # a DIFFERENT teacher (other weights) gets its own server
        other = jax.tree.map(lambda x: x + 1e-3, tparams)
        c = shared_teacher_server(cfg, teacher_params=other, warn=False)
        assert c is not a
    finally:
        _SHARED_TEACHERS.clear()
