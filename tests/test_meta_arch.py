import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train import build_optimizer, build_schedules
from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
from dinov3_tpu.train.train_step import TrainState, make_train_step

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.1", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=32", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=32", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


def make_setup(extra=(), B=4):
    cfg = smol_cfg(extra)
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    params = meta.init_params(jax.random.key(0), batch)
    return cfg, meta, batch, params


def test_init_params_structure():
    _, meta, batch, params = make_setup()
    assert set(params) == {"student", "teacher"}
    for side in ("student", "teacher"):
        assert set(params[side]) == {"backbone", "dino_head", "ibot_head"}
    # teacher starts as an exact copy of the student
    for a, b in zip(jax.tree.leaves(params["student"]),
                    jax.tree.leaves(params["teacher"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_forward_losses_finite_and_complete():
    _, meta, batch, params = make_setup()
    rngs = {"drop_path": jax.random.key(1), "rope": jax.random.key(2),
            "dropout": jax.random.key(3)}
    total, (loss_dict, _) = meta.forward(
        params["student"], {"teacher": params["teacher"]}, batch,
        teacher_temp=0.07, state=meta.init_state(), iteration=0, rngs=rngs,
    )
    for key in ("dino_local_crops_loss", "dino_global_crops_loss",
                "koleo_loss", "ibot_loss", "total_loss"):
        assert key in loss_dict, key
        assert np.isfinite(float(loss_dict[key])), key
    assert float(total) == pytest.approx(float(loss_dict["total_loss"]))


def test_gradients_touch_all_student_submodules():
    _, meta, batch, params = make_setup()
    rngs = {"drop_path": jax.random.key(1), "rope": jax.random.key(2),
            "dropout": jax.random.key(3)}

    def loss_fn(sp):
        return meta.forward(
            sp, {"teacher": params["teacher"]}, batch, teacher_temp=0.07,
            state=meta.init_state(), iteration=0, rngs=rngs)[0]

    grads = jax.grad(loss_fn)(params["student"])
    for sub in ("backbone", "dino_head", "ibot_head"):
        leaves = jax.tree.leaves(grads[sub])
        total = sum(float(jnp.sum(jnp.abs(l))) for l in leaves)
        assert total > 0, f"no gradient reached {sub}"
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves), sub


def test_train_step_learns_and_teacher_moves():
    cfg, meta, batch, params = make_setup()
    sched = build_schedules(cfg)
    opt = build_optimizer(cfg, params["student"], sched)
    state = TrainState(params, opt.init(params["student"]),
                       meta.init_state(), jnp.zeros((), jnp.int32))
    step = jax.jit(make_train_step(meta, opt, clip_grad=3.0))
    rng = jax.random.key(42)
    teacher_before = jax.tree.leaves(state.params["teacher"])[0].copy()
    losses = []
    for i in range(8):
        scal = sched.at(i)
        scalars = {"teacher_temp": jnp.asarray(scal["teacher_temp"], jnp.float32),
                   "momentum": jnp.asarray(0.9, jnp.float32)}
        state, metrics = step(state, batch, scalars, rng)
        losses.append(float(metrics["total_loss"]))
    assert int(state.step) == 8
    # same batch repeated: loss must drop
    assert losses[-1] < losses[0], losses
    # teacher EMA fed back (reference bug §2.9.1 fixed)
    teacher_after = jax.tree.leaves(state.params["teacher"])[0]
    assert not np.allclose(np.asarray(teacher_before), np.asarray(teacher_after))
    # teacher remains a blend, not equal to student
    student_after = jax.tree.leaves(state.params["student"])[0]
    assert not np.allclose(np.asarray(teacher_after), np.asarray(student_after))


def test_softmax_center_mode():
    _, meta, batch, params = make_setup(
        extra=["train.centering=softmax_center"])
    rngs = {"drop_path": jax.random.key(1), "rope": jax.random.key(2),
            "dropout": jax.random.key(3)}
    state0 = meta.init_state()
    total, (loss_dict, state1) = meta.forward(
        params["student"], {"teacher": params["teacher"]}, batch,
        teacher_temp=0.07, state=state0, iteration=0, rngs=rngs,
    )
    assert np.isfinite(float(total))
    assert not np.allclose(np.asarray(state1["dino_center"]),
                           np.asarray(state0["dino_center"]))


def test_gram_loss_path():
    _, meta, batch, params = make_setup(
        extra=["gram.use_loss=true", "gram.it_load_ema_teacher=0",
               "crops.gram_teacher_crops_size=16"])
    assert "gram" in params
    rngs = {"drop_path": jax.random.key(1), "rope": jax.random.key(2),
            "dropout": jax.random.key(3)}
    total, (loss_dict, _) = meta.forward(
        params["student"],
        {"teacher": params["teacher"], "gram": params["gram"]},
        batch, teacher_temp=0.07, state=meta.init_state(), iteration=0,
        rngs=rngs,
    )
    assert "gram_loss" in loss_dict
    assert np.isfinite(float(loss_dict["gram_loss"]))


def test_masking_buffers_consistency():
    cfg = smol_cfg()
    b = make_synthetic_batch(cfg, 4, seed=1)
    masks, idx, w, valid = (b["masks"], b["mask_indices"], b["mask_weights"],
                            b["mask_valid"])
    for i in range(masks.shape[0]):
        n = masks[i].sum()
        k = valid[i].sum()
        assert k == min(n, idx.shape[1])
        if k:
            # indices point at masked tokens, weights sum to ~1 per image
            assert masks[i][idx[i][valid[i]]].all()
            np.testing.assert_allclose(w[i].sum(), 1.0, rtol=1e-5)
        else:
            assert w[i].sum() == 0
