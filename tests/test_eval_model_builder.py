"""build_model_for_eval: fresh init and checkpoint-restored teacher."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.models import build_model_for_eval

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
]


def test_eval_build_fresh():
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL)
    model, params = build_model_for_eval(cfg)
    out = model.apply(
        {"params": params}, jnp.zeros((1, 16, 16, 3)), deterministic=True
    )
    assert out["x_norm_clstoken"].shape == (1, 64)


@pytest.mark.slow
def test_eval_build_from_checkpoint(tmp_path):
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    state, _ = setup.step_fn(
        setup.state, put_batch(batch, setup.batch_shardings),
        setup.scalars(0), jax.random.key(0),
    )
    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    ckpt.save(1, state)
    ckpt.close()

    model, params = build_model_for_eval(cfg, str(tmp_path / "ckpt"))
    want = jax.tree.leaves(state.params["teacher"]["backbone"])
    got = jax.tree.leaves(params)
    assert len(want) == len(got)
    for w, g in zip(want, got):
        assert np.allclose(np.asarray(w), np.asarray(g))
