"""Distillation (frozen bigger teacher) and multi-distillation subgroup
resolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train.multidistillation import (
    enumerate_subgroup_ranks,
    setup_multidistillation,
)

SMOL = [
    "student.patch_size=4", "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
]


def _teacher_yaml(tmp_path, arch="vit_test_big", hidden=48):
    recipe = {
        "student": {"arch": arch, "patch_size": 4, "drop_path_rate": 0.0},
        "dino": {"head_n_prototypes": 64, "head_hidden_dim": hidden,
                 "head_bottleneck_dim": 16},
        "ibot": {"head_n_prototypes": 64, "head_hidden_dim": hidden,
                 "head_bottleneck_dim": 16},
        "crops": {"global_crops_size": 16, "local_crops_size": 8,
                  "local_crops_number": 2},
        "optim": {"scaling_rule": "none"},
    }
    path = tmp_path / "teacher.yaml"
    path.write_text(yaml.safe_dump(recipe))
    return str(path)


def test_distillation_step_with_frozen_bigger_teacher(tmp_path):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "student.arch=vit_test",
        "optim.warmup_epochs=0",  # lr > 0 at step 0 so the student moves
        "distillation.enabled=true",
        f"distillation.full_cfg_path={_teacher_yaml(tmp_path)}",
    ])
    from dinov3_tpu.train import build_train_setup, put_batch

    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    # teacher backbone is the bigger arch
    assert setup.meta.teacher_embed_dim == 96
    assert setup.meta.embed_dim == 64
    teacher_before = jax.tree.map(
        np.asarray, setup.state.params["teacher"])
    student_before = np.asarray(
        jax.tree.leaves(setup.state.params["student"])[0])

    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert jnp.isfinite(metrics["total_loss"])
    # frozen teacher: unchanged after the step
    teacher_after = jax.tree.map(np.asarray, state.params["teacher"])
    for a, b in zip(jax.tree.leaves(teacher_before),
                    jax.tree.leaves(teacher_after)):
        assert np.array_equal(a, b)
    # student did move
    s1 = jax.tree.leaves(state.params["student"])[0]
    assert not np.allclose(student_before, np.asarray(s1))


def test_distillation_prototype_mismatch_rejected(tmp_path):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "student.arch=vit_test",
        "dino.head_n_prototypes=128",
        "distillation.enabled=true",
        f"distillation.full_cfg_path={_teacher_yaml(tmp_path)}",
    ])
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    with pytest.raises(ValueError, match="head_n_prototypes"):
        SSLMetaArch(cfg)


def test_load_teacher_params_partial_restore(tmp_path):
    """``load_teacher_params`` restores ONLY the teacher branch out of a
    full train-state checkpoint — the partial restore that TypeError'd
    on a raw ``partial_restore=True`` kwarg under older orbax before the
    version gate (checkpoint.pytree_restore_args). Fast arm of the @slow
    end-to-end test below: the teacher state is checkpointed at init,
    no pretraining step."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import build_train_setup
    from dinov3_tpu.train.distillation import load_teacher_params

    t_cfg = get_default_config()
    apply_dot_overrides(t_cfg, SMOL + [
        "student.arch=vit_test_big",
        "dino.head_hidden_dim=48", "ibot.head_hidden_dim=48",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(t_cfg, 4, seed=0).items()}
    t_setup = build_train_setup(t_cfg, batch)
    ckpt_dir = str(tmp_path / "teacher_ckpt")
    ckpt = Checkpointer(ckpt_dir, async_save=False)
    ckpt.save(1, t_setup.state)
    ckpt.close()

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "student.arch=vit_test",
        "distillation.enabled=true",
        f"distillation.full_cfg_path={_teacher_yaml(tmp_path, hidden=48)}",
        f"distillation.checkpoint_path={ckpt_dir}",
    ])
    setup = build_train_setup(cfg, batch)
    # different init seeds upstream: the restore must actually overwrite
    before = jax.tree.leaves(setup.state.params["teacher"])
    state = load_teacher_params(cfg, setup.state, setup.state_shardings)
    want = jax.tree.leaves(t_setup.state.params["teacher"])
    got = jax.tree.leaves(state.params["teacher"])
    assert len(want) == len(got) == len(before)
    for w, g in zip(want, got):
        assert np.array_equal(np.asarray(w), np.asarray(g))
    # student branch untouched
    for a, b in zip(jax.tree.leaves(setup.state.params["student"]),
                    jax.tree.leaves(state.params["student"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_load_teacher_params_from_checkpoint(tmp_path):
    """Pretrain a tiny teacher, checkpoint it, then restore it as the
    frozen teacher of a distillation run."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import build_train_setup, put_batch
    from dinov3_tpu.train.distillation import load_teacher_params

    # 1) teacher pretrain run (vit_test_big as its own student)
    t_cfg = get_default_config()
    apply_dot_overrides(t_cfg, SMOL + [
        "student.arch=vit_test_big",
        "dino.head_hidden_dim=48", "ibot.head_hidden_dim=48",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(t_cfg, 4, seed=0).items()}
    t_setup = build_train_setup(t_cfg, batch)
    t_state, _ = t_setup.step_fn(
        t_setup.state, put_batch(batch, t_setup.batch_shardings),
        t_setup.scalars(0), jax.random.key(0),
    )
    ckpt_dir = str(tmp_path / "teacher_ckpt")
    ckpt = Checkpointer(ckpt_dir, async_save=False)
    ckpt.save(1, t_state)
    ckpt.close()

    # 2) distillation run restoring that teacher
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "student.arch=vit_test",
        "distillation.enabled=true",
        f"distillation.full_cfg_path={_teacher_yaml(tmp_path, hidden=48)}",
        f"distillation.checkpoint_path={ckpt_dir}",
    ])
    setup = build_train_setup(cfg, batch)
    state = load_teacher_params(cfg, setup.state, setup.state_shardings)
    want = jax.tree.leaves(t_state.params["teacher"])
    got = jax.tree.leaves(state.params["teacher"])
    assert len(want) == len(got)
    for w, g in zip(want, got):
        assert np.allclose(np.asarray(w), np.asarray(g))


# ------------------------------------------------------ multidistillation


def test_enumerate_subgroup_ranks():
    assert enumerate_subgroup_ranks([(0, 2), (2, 3)]) == ((0, 1), (2,))
    with pytest.raises(ValueError):
        enumerate_subgroup_ranks([(3, 3)])


def test_setup_multidistillation_assignment(tmp_path):
    student_yaml = tmp_path / "vits.yaml"
    student_yaml.write_text(yaml.safe_dump({
        "student": {"arch": "vit_test", "patch_size": 4},
        "optim": {"scaling_rule": "none"},
    }))
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "multidistillation.enabled=true",
        "multidistillation.global_batch_size=8",
    ])
    cfg.multidistillation.students = [
        {"name": "a", "config_path": str(student_yaml), "ranks_range": [0, 2]},
        {"name": "b", "config_path": str(student_yaml), "ranks_range": [2, 4]},
    ]
    got = {}
    for rank in range(4):
        a = setup_multidistillation(
            cfg, rank, 4, base_output_dir=str(tmp_path / "out"))
        got[rank] = (a.name, a.group_rank)
        assert a.cfg.train.batch_size_per_device == 2
        assert a.cfg.student.arch == "vit_test"
        assert a.output_dir.endswith(a.name)
    assert got == {0: ("a", 0), 1: ("a", 1), 2: ("b", 0), 3: ("b", 1)}

    cfg.multidistillation.students[1]["ranks_range"] = [2, 5]
    with pytest.raises(ValueError, match="partition"):
        setup_multidistillation(cfg, 0, 4, base_output_dir=str(tmp_path))


@pytest.mark.slow
def test_multidistillation_end_to_end_two_groups(tmp_path):
    """Two rank-span groups each train a *different* student arch
    end-to-end from one launch (reference spec:
    dinov3_jax/models/temp.py:109-170 + vitl16_lvd1689m_distilled.yaml
    rank ranges; the reference's meta-arch was an empty stub)."""
    from dinov3_tpu.run import LocalLauncher

    s0 = tmp_path / "s0.yaml"
    s0.write_text(yaml.safe_dump({
        "student": {"arch": "vit_test", "patch_size": 4},
    }))
    s1 = tmp_path / "s1.yaml"
    s1.write_text(yaml.safe_dump({
        "student": {"arch": "vit_test_big", "patch_size": 4,
                    "ffn_layer": "swiglu"},
    }))
    base = tmp_path / "base.yaml"
    base.write_text(yaml.safe_dump({
        "multidistillation": {
            "enabled": True,
            "global_batch_size": 4,
            "students": [
                {"name": "s0", "config_path": str(s0),
                 "ranks_range": [0, 1]},
                {"name": "s1", "config_path": str(s1),
                 "ranks_range": [1, 2]},
            ],
        },
    }))
    target = tmp_path / "md.py"
    target.write_text(
        "def main(argv):\n"
        "    import jax, pathlib\n"
        "    from dinov3_tpu.train.train import main as train_main\n"
        "    out = train_main(argv)\n"
        "    assert out['iterations'] == 2, out\n"
        "    pathlib.Path(argv[3] + f'/done{jax.process_index()}').touch()\n"
    )
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    LocalLauncher(2, port=12503).launch(
        str(target),
        [
            "--config-file", str(base),
            "--output-dir", str(run_dir),
            "--no-resume",
            "crops.global_crops_size=16", "crops.local_crops_size=8",
            "crops.local_crops_number=2",
            "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
            "dino.head_bottleneck_dim=16",
            "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
            "ibot.head_bottleneck_dim=16",
            "train.OFFICIAL_EPOCH_LENGTH=2",
            "optim.epochs=1", "optim.warmup_epochs=0",
            "optim.scaling_rule=none", "data.backend=synthetic",
        ],
        timeout_s=420.0,
    )
    assert (run_dir / "done0").exists() and (run_dir / "done1").exists()
    # each group's primary host wrote its own student's metrics + checkpoint
    for name in ("s0", "s1"):
        assert (run_dir / name / "training_metrics.json").exists(), name
        ckpts = list((run_dir / name / "ckpt").iterdir())
        assert ckpts, f"no checkpoint for {name}"

    # ---- resume leg (ADVICE r2): same run dir, no --no-resume, more
    # epochs. Each group is a one-process subgroup of a 2-process job, so
    # restore exercises the numpy-save mirror path; eval_period fires the
    # in-training eval with subgroup-scoped data sharding (a global
    # collective here would deadlock across the two groups).
    target2 = tmp_path / "md_resume.py"
    target2.write_text(
        "def main(argv):\n"
        "    import jax, pathlib\n"
        "    from dinov3_tpu.train.train import main as train_main\n"
        "    out = train_main(argv)\n"
        "    assert out['iterations'] == 4, out\n"
        "    pathlib.Path(argv[3] + f'/resumed{jax.process_index()}')"
        ".touch()\n"
    )
    LocalLauncher(2, port=12504).launch(
        str(target2),
        [
            "--config-file", str(base),
            "--output-dir", str(run_dir),
            "crops.global_crops_size=16", "crops.local_crops_size=8",
            "crops.local_crops_number=2",
            "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
            "dino.head_bottleneck_dim=16",
            "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
            "ibot.head_bottleneck_dim=16",
            "train.OFFICIAL_EPOCH_LENGTH=2",
            "optim.epochs=2", "optim.warmup_epochs=0",
            "optim.scaling_rule=none", "data.backend=synthetic",
            "evaluation.eval_period_iterations=3",
            "+evaluation.train_dataset_path="
            "Synthetic:split=TRAIN:size=16:image_size=16:n_classes=2",
            "+evaluation.val_dataset_path="
            "Synthetic:split=VAL:size=8:image_size=16:n_classes=2",
        ],
        timeout_s=420.0,
    )
    assert (run_dir / "resumed0").exists() and (run_dir / "resumed1").exists()


def test_checkpointer_local_npz_backend(tmp_path):
    """The one-host-subgroup backend (orbax's numpy handlers hardcode
    process 0 writes — checkpoint.py) must roundtrip bf16 leaves, apply
    retention, ignore foreign step dirs, and support params-only restore."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train.train_step import TrainState

    ck = Checkpointer(str(tmp_path / "ck"), max_to_keep=2, async_save=False)
    # force the subgroup backend in a 1-process test (the production
    # detection needs process_count > 1 and is covered by the 2-process
    # multidistillation e2e); close the orbax manager it won't use
    ck.manager.close()
    ck.manager = None
    ck._local = True

    def state_at(v):
        return TrainState(
            params={"w": jnp.full((4, 4), v, jnp.bfloat16),
                    "b": jnp.full((3,), v, jnp.float32)},
            opt_state=(jnp.asarray(v, jnp.int32),),
            center_state={"c": jnp.zeros((2,))},
            step=jnp.asarray(v),
        )

    # a pre-upgrade orbax-layout dir must not be announced as resumable
    (tmp_path / "ck" / "7").mkdir(parents=True)
    assert ck.latest_step() is None

    for s in (1, 2, 3):
        ck.save(s, state_at(s))
    assert ck.latest_step() == 3
    import os

    kept = sorted(d for d in os.listdir(tmp_path / "ck")
                  if (tmp_path / "ck" / d / "state.npz").exists())
    assert kept == ["2", "3"], kept  # max_to_keep=2

    restored = ck.restore(state_at(0), step=3)
    assert restored.params["w"].dtype == jnp.bfloat16
    assert float(jnp.asarray(restored.params["w"], jnp.float32).mean()) == 3
    assert float(restored.params["b"][0]) == 3
    assert int(restored.step) == 3

    ponly = ck.restore_params_only(state_at(0), step=2)
    assert float(jnp.asarray(ponly.params["w"], jnp.float32).mean()) == 2
    assert int(ponly.step) == 0  # non-param state untouched
    ck.close()
