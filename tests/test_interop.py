"""Torch-checkpoint conversion: build a Meta-layout state_dict from a real
init, convert it back, and verify numerical forward equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.interop import (
    convert_torch_backbone_state_dict,
    load_backbone_from_torch,
)
from dinov3_tpu.models.vision_transformer import vit_test


def _fake_torch_sd_from_params(params: dict) -> dict:
    """Inverse of the converter: our tree -> Meta torch key layout."""
    sd = {}

    def walk(node, path):
        for k, v in node.items():
            p = path + [k]
            if isinstance(v, dict):
                walk(v, p)
                continue
            v = np.asarray(v)
            key = ".".join(p)
            key = key.replace("blocks_", "blocks.")
            if key == "patch_embed.kernel":
                sd["patch_embed.proj.weight"] = v.transpose(3, 2, 0, 1)
            elif key == "patch_embed.bias":
                sd["patch_embed.proj.bias"] = v
            elif key == "mask_token":
                sd["mask_token"] = v.reshape(1, -1)
            elif key.endswith("attn.qkv_kernel"):
                sd[key.replace("qkv_kernel", "qkv.weight")] = v.T
            elif key.endswith("attn.qkv_bias"):
                sd[key.replace("qkv_bias", "qkv.bias")] = v
            elif key.endswith("attn.proj_kernel"):
                sd[key.replace("proj_kernel", "proj.weight")] = v.T
            elif key.endswith("attn.proj_bias"):
                sd[key.replace("proj_bias", "proj.bias")] = v
            elif key.endswith(".scale"):
                sd[key[: -len(".scale")] + ".weight"] = v
            elif key.endswith(".kernel"):
                sd[key[: -len(".kernel")] + ".weight"] = v.T
            else:
                sd[key] = v

    walk(params, [])
    # buffers the converter must skip
    sd["rope_embed.periods"] = np.ones(4, np.float32)
    return sd


@pytest.fixture(scope="module")
def model_and_params():
    model = vit_test(patch_size=4, n_storage_tokens=4, drop_path_rate=0.0)
    import flax.linen as nn

    x = jnp.zeros((1, 16, 16, 3))
    variables = nn.meta.unbox(model.init(jax.random.key(1), x))
    # give params non-trivial values so equivalence is meaningful
    leaves, treedef = jax.tree.flatten(variables["params"])
    rng = np.random.default_rng(0)
    leaves = [
        jnp.asarray(rng.standard_normal(l.shape), jnp.float32) * 0.05
        for l in leaves
    ]
    params = jax.tree.unflatten(treedef, leaves)
    return model, params


def test_roundtrip_forward_equivalence(model_and_params):
    model, params = model_and_params
    sd = _fake_torch_sd_from_params(params)
    restored = load_backbone_from_torch(
        model, sd, example_shape=(1, 16, 16, 3)
    )
    x = jax.random.normal(jax.random.key(2), (2, 16, 16, 3))
    want = model.apply({"params": params}, x, deterministic=True)
    got = model.apply(restored, x, deterministic=True)
    assert np.allclose(
        np.asarray(want["x_norm_clstoken"], np.float32),
        np.asarray(got["x_norm_clstoken"], np.float32),
        atol=1e-6,
    )
    assert np.allclose(
        np.asarray(want["x_norm_patchtokens"], np.float32),
        np.asarray(got["x_norm_patchtokens"], np.float32),
        atol=1e-6,
    )


def test_strict_mode_reports_missing(model_and_params):
    model, params = model_and_params
    sd = _fake_torch_sd_from_params(params)
    del sd["cls_token"]
    sd["mystery.weight"] = np.zeros((3, 3), np.float32)
    with pytest.raises(ValueError, match="missing"):
        load_backbone_from_torch(model, sd, example_shape=(1, 16, 16, 3))
    # non-strict drops the extras and keeps going
    restored = load_backbone_from_torch(
        model, sd, example_shape=(1, 16, 16, 3), strict=False
    )
    assert "cls_token" not in restored["params"]
    assert "mystery" not in restored["params"]


def test_convert_skips_buffers(model_and_params):
    _, params = model_and_params
    sd = _fake_torch_sd_from_params(params)
    out = convert_torch_backbone_state_dict(sd)
    assert "rope_embed" not in out
