"""The op-level flash-vs-dense crossover harness
(scripts/crossover_attention.py): the executable definition of the
``kernels.flash_min_seq`` dispatch threshold.

The threshold-derivation functions are cheap and run in the default
selection; the actual measurement loop is slow-marked and runs the
dense-XLA arm on the CPU backend (the Pallas arm records an error row
there and is skipped by the summary — exactly the degradation the
script promises on non-TPU backends)."""

import importlib.util
import os

import pytest

_PATH = os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "crossover_attention.py")


def _load():
    spec = importlib.util.spec_from_file_location(
        "crossover_attention", _PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_recommended_flash_min_seq_definition():
    xo = _load()
    # flash wins from 2309 up: threshold = smallest winning N
    summary = [
        {"N": 201, "xla_ms": 1.0, "flash_ms": 1.5, "flash_speedup": 0.667},
        {"N": 1029, "xla_ms": 4.0, "flash_ms": 5.0, "flash_speedup": 0.8},
        {"N": 2309, "xla_ms": 20.0, "flash_ms": 16.0, "flash_speedup": 1.25},
        {"N": 4096, "xla_ms": 60.0, "flash_ms": 40.0, "flash_speedup": 1.5},
    ]
    assert xo.recommended_flash_min_seq(summary) == 2309
    # dense wins everywhere: no threshold (keep dense at every N)
    never = [dict(r, flash_speedup=0.9) for r in summary]
    assert xo.recommended_flash_min_seq(never) is None
    # exact tie counts as a flash win (>= 1)
    tie = [dict(summary[0], flash_speedup=1.0)]
    assert xo.recommended_flash_min_seq(tie) == 201


def test_crossover_summary_pairs_and_skips_errors():
    xo = _load()
    records = [
        {"B": 2, "N": 64, "impl": "xla", "ms": 2.0, "compile_s": 0.1},
        {"B": 2, "N": 64, "impl": "pallas", "ms": 1.0, "compile_s": 0.1},
        {"B": 2, "N": 128, "impl": "xla", "ms": 3.0, "compile_s": 0.1},
        {"B": 2, "N": 128, "impl": "pallas", "error": "no TPU"},
    ]
    summary = xo.crossover_summary(records)
    assert summary == [{"N": 64, "xla_ms": 2.0, "flash_ms": 1.0,
                        "flash_speedup": 2.0}]


def test_parse_cases():
    xo = _load()
    assert xo.parse_cases("16x201,4x1029") == [(16, 201), (4, 1029)]


@pytest.mark.slow
def test_measure_crossover_collects_on_cpu():
    """The harness runs end-to-end on the CPU backend: dense-XLA rows
    measure, Pallas rows degrade to error records, and the summary/
    threshold pipeline consumes the result."""
    xo = _load()
    records = xo.measure_crossover(cases=[(2, 64)], steps=1, warmup=0)
    assert {r["impl"] for r in records} == {"xla", "pallas"}
    xla = [r for r in records if r["impl"] == "xla"][0]
    assert "ms" in xla and xla["ms"] > 0
    summary = xo.crossover_summary(records)
    # CPU: pallas errored -> no pair; threshold degrades to None
    if not summary:
        assert xo.recommended_flash_min_seq(summary) is None
    else:  # a CPU-lowering pallas build would pair up; still well-formed
        assert {"N", "xla_ms", "flash_ms", "flash_speedup"} <= set(summary[0])
