"""The op-level flash-vs-dense crossover harness
(scripts/crossover_attention.py): the executable definition of the
``kernels.flash_min_seq`` dispatch threshold.

The threshold-derivation functions are cheap and run in the default
selection; the actual measurement loop is slow-marked and runs the
dense-XLA arm on the CPU backend (the Pallas arm records an error row
there and is skipped by the summary — exactly the degradation the
script promises on non-TPU backends)."""

import importlib.util
import os

import pytest

_PATH = os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "crossover_attention.py")


def _load():
    spec = importlib.util.spec_from_file_location(
        "crossover_attention", _PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_recommended_flash_min_seq_definition():
    xo = _load()
    # flash wins from 2309 up: threshold = smallest winning N
    summary = [
        {"N": 201, "xla_ms": 1.0, "flash_ms": 1.5, "flash_speedup": 0.667},
        {"N": 1029, "xla_ms": 4.0, "flash_ms": 5.0, "flash_speedup": 0.8},
        {"N": 2309, "xla_ms": 20.0, "flash_ms": 16.0, "flash_speedup": 1.25},
        {"N": 4096, "xla_ms": 60.0, "flash_ms": 40.0, "flash_speedup": 1.5},
    ]
    assert xo.recommended_flash_min_seq(summary) == 2309
    # dense wins everywhere: no threshold (keep dense at every N)
    never = [dict(r, flash_speedup=0.9) for r in summary]
    assert xo.recommended_flash_min_seq(never) is None
    # exact tie counts as a flash win (>= 1)
    tie = [dict(summary[0], flash_speedup=1.0)]
    assert xo.recommended_flash_min_seq(tie) == 201


def test_crossover_summary_pairs_and_skips_errors():
    xo = _load()
    records = [
        {"B": 2, "N": 64, "impl": "xla", "ms": 2.0, "compile_s": 0.1},
        {"B": 2, "N": 64, "impl": "pallas", "ms": 1.0, "compile_s": 0.1},
        {"B": 2, "N": 128, "impl": "xla", "ms": 3.0, "compile_s": 0.1},
        {"B": 2, "N": 128, "impl": "pallas", "error": "no TPU"},
    ]
    summary = xo.crossover_summary(records)
    assert summary == [{"N": 64, "xla_ms": 2.0, "flash_ms": 1.0,
                        "flash_speedup": 2.0}]


def test_parse_cases():
    xo = _load()
    assert xo.parse_cases("16x201,4x1029") == [(16, 201), (4, 1029)]


def test_committed_crossover_artifact_pins_flash_min_seq():
    """CROSSOVER_r19.json is the committed source of the
    ``kernels.flash_min_seq=auto`` default: well-formed, produced by the
    harness under test, and its recommendation round-trips through the
    config resolver exactly as the threshold definition says."""
    import json

    from dinov3_tpu.configs.config import (
        CROSSOVER_ARTIFACT,
        FLASH_NEVER_SEQ,
        resolve_flash_min_seq,
    )

    assert CROSSOVER_ARTIFACT.exists(), (
        "CROSSOVER_r19.json missing — re-derive with "
        "scripts/crossover_attention.py CROSSOVER_r19.json")
    with open(CROSSOVER_ARTIFACT) as f:
        doc = json.load(f)
    assert doc["generated_by"] == "scripts/crossover_attention.py"
    assert {"platform", "records", "crossover",
            "recommended_flash_min_seq"} <= set(doc)
    # the recommendation must be re-derivable from the committed summary
    xo = _load()
    rec = doc["recommended_flash_min_seq"]
    assert rec == xo.recommended_flash_min_seq(doc["crossover"])
    # and the resolver dispatches on it: a measured N passes through, a
    # null (flash never won — the CPU-harness verdict) means dense
    # everywhere via the effectively-infinite sentinel
    resolved = resolve_flash_min_seq("auto")
    assert resolved == (FLASH_NEVER_SEQ if rec is None else int(rec))


def test_resolve_flash_min_seq_paths(tmp_path):
    """The resolver's four paths: int pass-through, string override,
    auto-from-artifact (int and null), unreadable-artifact fallback."""
    import json
    import warnings

    from dinov3_tpu.configs.config import (
        FLASH_NEVER_SEQ,
        resolve_flash_min_seq,
    )

    assert resolve_flash_min_seq(2048) == 2048
    assert resolve_flash_min_seq(0) == 0
    assert resolve_flash_min_seq("2048") == 2048
    good = tmp_path / "xover.json"
    good.write_text(json.dumps({"recommended_flash_min_seq": 2309}))
    assert resolve_flash_min_seq("auto", artifact=good) == 2309
    never = tmp_path / "never.json"
    never.write_text(json.dumps({"recommended_flash_min_seq": None}))
    assert resolve_flash_min_seq("auto", artifact=never) == FLASH_NEVER_SEQ
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got = resolve_flash_min_seq("auto", artifact=tmp_path / "nope.json")
    assert got == 0
    assert any("crossover artifact" in str(w.message) for w in caught)


@pytest.mark.slow
def test_measure_crossover_collects_on_cpu():
    """The harness runs end-to-end on the CPU backend: dense-XLA rows
    measure, Pallas rows degrade to error records, and the summary/
    threshold pipeline consumes the result."""
    xo = _load()
    records = xo.measure_crossover(cases=[(2, 64)], steps=1, warmup=0)
    assert {r["impl"] for r in records} == {"xla", "pallas"}
    xla = [r for r in records if r["impl"] == "xla"][0]
    assert "ms" in xla and xla["ms"] > 0
    summary = xo.crossover_summary(records)
    # CPU: pallas errored -> no pair; threshold degrades to None
    if not summary:
        assert xo.recommended_flash_min_seq(summary) is None
    else:  # a CPU-lowering pallas build would pair up; still well-formed
        assert {"N", "xla_ms", "flash_ms", "flash_speedup"} <= set(summary[0])
