"""Unit tests for bench.py's harness helpers (the measurement path is
round evidence — its plumbing gets the same test rigor as the library)."""

import importlib.util
import os
import sys

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_split_overrides_plain():
    assert bench._split_overrides("a=1,b=2") == ["a=1", "b=2"]


def test_split_overrides_brackets():
    s = "crops.global_crops_size=[512,768],kernels.flash_attention=xla"
    assert bench._split_overrides(s) == [
        "crops.global_crops_size=[512,768]",
        "kernels.flash_attention=xla",
    ]


def test_split_overrides_nested_and_trailing():
    assert bench._split_overrides("x=[(1,2),(3,4)],y=5,") == [
        "x=[(1,2),(3,4)]", "y=5",
    ]
    assert bench._split_overrides("") == []


def test_tpu_required_env_rules(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert bench._tpu_required()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not bench._tpu_required()
    # unset: depends on whether the axon plugin is registered in this
    # process — assert it agrees with the registry rather than a constant
    monkeypatch.delenv("JAX_PLATFORMS")
    from jax._src import xla_bridge

    expected = "axon" in getattr(xla_bridge, "_backend_factories", {})
    assert bench._tpu_required() == expected


def _proc_state(pid: int) -> str | None:
    """Process state letter from /proc, or None if the pid is gone.
    A 'Z' zombie counts as dead for our purposes (killed but not yet
    reaped by init)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ")[-1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return None


def _supervise_with_victim(monkeypatch, capsys, victim_prog: str,
                           env: dict[str, str]):
    """Drive the REAL supervisor end-to-end with a victim child program
    (BENCH_CHILD_ARGV) standing in for the measurement child."""
    import json

    monkeypatch.setenv(
        "BENCH_CHILD_ARGV",
        json.dumps([sys.executable, "-c", victim_prog]),
    )
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    rc = bench._supervise()
    out = capsys.readouterr().out.strip()
    assert out, "supervisor must always print a final JSON line"
    return rc, json.loads(out.splitlines()[-1])


def test_supervise_infra_fast_fail(monkeypatch, capsys):
    """A child reporting rc=3 (backend unreachable) must stop the ladder
    at the FIRST rung and leave an attributable 'tunnel down' record —
    the BENCH_r03 dead-tunnel scenario, which previously walked all
    rungs into the driver's rc=124."""
    import time

    t0 = time.time()
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import sys; sys.exit(3)",
        {"BENCH_ATTEMPT_TIMEOUT": "600"},
    )
    assert rc == bench.RC_INFRA_DOWN
    assert "axon tunnel down" in rec["skipped"]
    assert rec["value"] is None
    assert rec["failed_rungs"] == []  # stopped before burning any rung
    # one victim spawn (~5-10s sitecustomize preimport), not 3 x timeout
    assert time.time() - t0 < 60


def test_supervise_budget_cap_always_prints(monkeypatch, capsys):
    """When the total budget cannot fit another rung, the supervisor
    stops and still prints a final JSON line (rc=5) instead of letting
    an external backstop kill it recordless."""
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import time; time.sleep(600)",
        {"BENCH_ATTEMPT_TIMEOUT": "20", "BENCH_TOTAL_BUDGET": "25"},
    )
    assert rc == bench.RC_BUDGET_EXHAUSTED
    assert "budget" in rec["skipped"]
    assert len(rec["failed_rungs"]) == 1  # rung 1 timed out, rung 2 never ran
    assert "timed out" in rec["failed_rungs"][0]


def test_supervise_program_failure_walks_ladder(monkeypatch, capsys):
    """A program crash (rc=1) is NOT infra: the ladder walks every rung
    and the final record names each rung's failure."""
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import sys; sys.exit(1)",
        {"BENCH_ATTEMPT_TIMEOUT": "600"},
    )
    assert rc == bench.RC_PROGRAM_FAILED
    assert len(rec["failed_rungs"]) == 3
    assert "not an infra failure" in rec["skipped"]


def test_run_attempt_kills_process_group(tmp_path):
    """_run_attempt (the real supervisor mechanism) must reap a hung
    grandchild on timeout — the orphaned-probe scenario."""
    import textwrap
    import time

    marker = str(tmp_path / "grandchild_pid")
    prog = textwrap.dedent(f"""
        import subprocess, sys, time
        subprocess.Popen([sys.executable, "-c",
            "import time, os\\n"
            "open({marker!r}, 'w').write(str(os.getpid()))\\n"
            "time.sleep(600)"])
        time.sleep(600)
    """)
    t0 = time.time()
    rc, out = bench._run_attempt(
        dict(os.environ), tmo=25.0, argv=[sys.executable, "-c", prog]
    )
    assert rc == 124
    # interpreter startup runs the axon sitecustomize (preimports jax,
    # ~5-10s per process, two levels deep) — the 25s budget covers it
    assert os.path.exists(marker), "grandchild never started within budget"
    gpid = int(open(marker).read())
    deadline = time.time() + 10
    while _proc_state(gpid) not in (None, "Z") and time.time() < deadline:
        time.sleep(0.2)
    assert _proc_state(gpid) in (None, "Z"), (
        f"grandchild {gpid} survived the group kill "
        f"(state={_proc_state(gpid)}, wall={time.time() - t0:.1f}s)"
    )


def test_supervise_midrun_stall_converts_to_infra(monkeypatch, capsys):
    """VERDICT r4 weak #5 / next #6: a tunnel that dies BETWEEN the init
    probe's success and the device work must still end in the
    attributable rc=3 fast-fail, not the external watchdog's rc=124.
    The victim child runs the REAL watchdog/stall-probe machinery with a
    simulated dead tunnel and a hung 'measure' phase; the real
    supervisor must see its rc=3 and stop the ladder at once."""
    import textwrap
    import time

    bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    victim = textwrap.dedent(f"""
        import importlib.util, time
        spec = importlib.util.spec_from_file_location(
            "bench", {os.path.abspath(bench_path)!r})
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        # simulated mid-run tunnel death: the re-probe always fails
        bench._probe_backend_subprocess = lambda t: "tunnel dead (simulated)"
        bench._tpu_required = lambda: True
        bench._PHASE["name"] = "measure"
        bench._PHASE["since"] = time.time() - 999  # long past stall_after
        bench._watchdog(period=0.2)
        time.sleep(600)  # the hung device fetch the watchdog must bound
    """)
    t0 = time.time()
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, victim,
        {"BENCH_ATTEMPT_TIMEOUT": "600",
         "BENCH_STALL_PROBE_AFTER": "1"},
    )
    assert rc == bench.RC_INFRA_DOWN
    assert "axon tunnel down" in rec["skipped"]
    assert rec["value"] is None
    # detected by the stall probe within seconds, not the 600s timeout
    assert time.time() - t0 < 60


def test_maybe_stall_probe_healthy_resets(monkeypatch):
    """A healthy re-probe during a slow phase must reset the strike
    count — a legitimately long compile on a live tunnel is never
    killed by one earlier flaky probe."""
    import time

    monkeypatch.setattr(bench, "_tpu_required", lambda: True)
    bench._PHASE["name"] = "compile"
    bench._PHASE["since"] = time.time() - 999
    try:
        state = {"last_probe": 0.0, "fails": 1}  # one earlier failure
        monkeypatch.setattr(
            bench, "_probe_backend_subprocess", lambda t: None)
        bench._maybe_stall_probe(state, stall_after=1.0, probe_tmo=1.0)
        assert state["fails"] == 0
        # outside device phases the count also resets and no probe runs
        bench._PHASE["name"] = "report"
        monkeypatch.setattr(
            bench, "_probe_backend_subprocess",
            lambda t: (_ for _ in ()).throw(AssertionError("probed")))
        state["fails"] = 1
        bench._maybe_stall_probe(state, stall_after=1.0, probe_tmo=1.0)
        assert state["fails"] == 0
    finally:
        bench._PHASE["name"] = "startup"
        bench._PHASE["since"] = time.time()


def test_supervise_budget_below_infra_floor_is_attributable(
        monkeypatch, capsys):
    """ADVICE r4: when the remaining budget shrinks a later rung's
    timeout below the child's infra-detection floor, the supervisor
    stops with a budget record instead of running a rung whose dead-
    tunnel outcome would be misrecorded as a program timeout. (The floor
    never blocks a caller-chosen small BENCH_ATTEMPT_TIMEOUT.)"""
    import time

    t0 = time.time()
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import time; time.sleep(600)",
        # tmo=700 > floor(650) > budget=350: the budget shrinks rung 1's
        # effective timeout to 350s — below the child's 650s worst-case
        # infra-detection time — so the supervisor must stop BEFORE
        # spawning a child whose dead-tunnel outcome could only be an
        # unattributable rc=124
        {"BENCH_ATTEMPT_TIMEOUT": "700", "BENCH_TOTAL_BUDGET": "350",
         "BENCH_PROBE_TIMEOUT": "270", "BENCH_INIT_RETRIES": "1"},
    )
    assert rc == bench.RC_BUDGET_EXHAUSTED
    assert "infra-detection floor" in rec["skipped"]
    assert rec["value"] is None
    assert time.time() - t0 < 30  # no child was ever spawned


def test_build_step_overrides_shared_contract():
    """scripts/count_flops.py counts FLOPs of bench.py's exact program
    through this builder — its env-independent output is the contract."""
    ov = bench.build_step_overrides("vit_large", 0)
    assert "student.arch=vit_large" in ov
    assert "student.n_storage_tokens=4" in ov
    assert not any(o.startswith("crops.") for o in ov)
    assert not any("drop_path_mode" in o for o in ov)  # config default rules
    ov = bench.build_step_overrides(
        "vit_large", 512, drop_path_mode="mask", probs="fp32",
        extra=["train.scan_layers=false"])
    assert "crops.global_crops_size=512" in ov
    assert "crops.local_crops_size=128" in ov
    assert "student.drop_path_mode=mask" in ov
    assert "compute_precision.probs_dtype=fp32" in ov
    assert ov[-1] == "train.scan_layers=false"
    # 768px: local crops floor at 96*2=192? no — max(96, 768//4)=192
    ov = bench.build_step_overrides("vit_large", 768)
    assert "crops.local_crops_size=192" in ov


def test_measure_calibration_fixed_program():
    """The calibration rung is a fixed program whose record lands in
    every bench JSON line (and thus every phases-JSONL row a queue
    harness embeds): assert the program tag is pinned and the measured
    fields are sane on whatever backend this suite runs."""
    import jax
    import jax.numpy as jnp

    calib = bench._measure_calibration(jax, jnp)
    assert calib["program"] == "matmul1024_bf16_chain_x10"
    assert calib["ms_per_matmul"] > 0
    assert calib["tflops"] > 0


def test_bench_guardrail_import_path():
    """bench.py warns through the same guardrail as config build."""
    import warnings

    from dinov3_tpu.configs.config import warn_bad_batch_tiling

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert warn_bad_batch_tiling(10) is not None   # the measured cliff
        assert warn_bad_batch_tiling(12) is None       # the bench default
        assert len(caught) == 1
