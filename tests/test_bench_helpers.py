"""Unit tests for bench.py's harness helpers (the measurement path is
round evidence — its plumbing gets the same test rigor as the library)."""

import importlib.util
import os
import sys

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_split_overrides_plain():
    assert bench._split_overrides("a=1,b=2") == ["a=1", "b=2"]


def test_split_overrides_brackets():
    s = "crops.global_crops_size=[512,768],kernels.flash_attention=xla"
    assert bench._split_overrides(s) == [
        "crops.global_crops_size=[512,768]",
        "kernels.flash_attention=xla",
    ]


def test_split_overrides_nested_and_trailing():
    assert bench._split_overrides("x=[(1,2),(3,4)],y=5,") == [
        "x=[(1,2),(3,4)]", "y=5",
    ]
    assert bench._split_overrides("") == []


def test_tpu_required_env_rules(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert bench._tpu_required()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not bench._tpu_required()
    # unset: depends on whether the axon plugin is registered in this
    # process — assert it agrees with the registry rather than a constant
    monkeypatch.delenv("JAX_PLATFORMS")
    from jax._src import xla_bridge

    expected = "axon" in getattr(xla_bridge, "_backend_factories", {})
    assert bench._tpu_required() == expected


def _proc_state(pid: int) -> str | None:
    """Process state letter from /proc, or None if the pid is gone.
    A 'Z' zombie counts as dead for our purposes (killed but not yet
    reaped by init)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ")[-1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return None


def test_run_attempt_kills_process_group(tmp_path):
    """_run_attempt (the real supervisor mechanism) must reap a hung
    grandchild on timeout — the orphaned-probe scenario."""
    import textwrap
    import time

    marker = str(tmp_path / "grandchild_pid")
    prog = textwrap.dedent(f"""
        import subprocess, sys, time
        subprocess.Popen([sys.executable, "-c",
            "import time, os\\n"
            "open({marker!r}, 'w').write(str(os.getpid()))\\n"
            "time.sleep(600)"])
        time.sleep(600)
    """)
    t0 = time.time()
    rc, out = bench._run_attempt(
        dict(os.environ), tmo=25.0, argv=[sys.executable, "-c", prog]
    )
    assert rc == 124
    # interpreter startup runs the axon sitecustomize (preimports jax,
    # ~5-10s per process, two levels deep) — the 25s budget covers it
    assert os.path.exists(marker), "grandchild never started within budget"
    gpid = int(open(marker).read())
    deadline = time.time() + 10
    while _proc_state(gpid) not in (None, "Z") and time.time() < deadline:
        time.sleep(0.2)
    assert _proc_state(gpid) in (None, "Z"), (
        f"grandchild {gpid} survived the group kill "
        f"(state={_proc_state(gpid)}, wall={time.time() - t0:.1f}s)"
    )
