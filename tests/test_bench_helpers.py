"""Unit tests for bench.py's harness helpers (the measurement path is
round evidence — its plumbing gets the same test rigor as the library)."""

import importlib.util
import os
import sys

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_split_overrides_plain():
    assert bench._split_overrides("a=1,b=2") == ["a=1", "b=2"]


def test_split_overrides_brackets():
    s = "crops.global_crops_size=[512,768],kernels.flash_attention=xla"
    assert bench._split_overrides(s) == [
        "crops.global_crops_size=[512,768]",
        "kernels.flash_attention=xla",
    ]


def test_split_overrides_nested_and_trailing():
    assert bench._split_overrides("x=[(1,2),(3,4)],y=5,") == [
        "x=[(1,2),(3,4)]", "y=5",
    ]
    assert bench._split_overrides("") == []


def test_tpu_required_env_rules(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert bench._tpu_required()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not bench._tpu_required()
    # unset: depends on whether the axon plugin is registered in this
    # process — assert it agrees with the registry rather than a constant
    monkeypatch.delenv("JAX_PLATFORMS")
    from jax._src import xla_bridge

    expected = "axon" in getattr(xla_bridge, "_backend_factories", {})
    assert bench._tpu_required() == expected


def _proc_state(pid: int) -> str | None:
    """Process state letter from /proc, or None if the pid is gone.
    A 'Z' zombie counts as dead for our purposes (killed but not yet
    reaped by init)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ")[-1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return None


def _supervise_with_victim(monkeypatch, capsys, victim_prog: str,
                           env: dict[str, str]):
    """Drive the REAL supervisor end-to-end with a victim child program
    (BENCH_CHILD_ARGV) standing in for the measurement child."""
    import json

    monkeypatch.setenv(
        "BENCH_CHILD_ARGV",
        json.dumps([sys.executable, "-c", victim_prog]),
    )
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    rc = bench._supervise()
    out = capsys.readouterr().out.strip()
    assert out, "supervisor must always print a final JSON line"
    return rc, json.loads(out.splitlines()[-1])


def test_supervise_infra_fast_fail(monkeypatch, capsys):
    """A child reporting rc=3 (backend unreachable) must stop the ladder
    at the FIRST rung and leave an attributable 'tunnel down' record —
    the BENCH_r03 dead-tunnel scenario, which previously walked all
    rungs into the driver's rc=124."""
    import time

    t0 = time.time()
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import sys; sys.exit(3)",
        {"BENCH_ATTEMPT_TIMEOUT": "600"},
    )
    assert rc == bench.RC_INFRA_DOWN
    assert "axon tunnel down" in rec["skipped"]
    assert rec["value"] is None
    assert rec["failed_rungs"] == []  # stopped before burning any rung
    # one victim spawn (~5-10s sitecustomize preimport), not 3 x timeout
    assert time.time() - t0 < 60


def test_supervise_budget_cap_always_prints(monkeypatch, capsys):
    """When the total budget cannot fit another rung, the supervisor
    stops and still prints a final JSON line (rc=5) instead of letting
    an external backstop kill it recordless."""
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import time; time.sleep(600)",
        {"BENCH_ATTEMPT_TIMEOUT": "20", "BENCH_TOTAL_BUDGET": "25"},
    )
    assert rc == bench.RC_BUDGET_EXHAUSTED
    assert "budget" in rec["skipped"]
    assert len(rec["failed_rungs"]) == 1  # rung 1 timed out, rung 2 never ran
    assert "timed out" in rec["failed_rungs"][0]


def test_supervise_program_failure_walks_ladder(monkeypatch, capsys):
    """A program crash (rc=1) is NOT infra: the ladder walks every rung
    and the final record names each rung's failure."""
    rc, rec = _supervise_with_victim(
        monkeypatch, capsys, "import sys; sys.exit(1)",
        {"BENCH_ATTEMPT_TIMEOUT": "600"},
    )
    assert rc == bench.RC_PROGRAM_FAILED
    assert len(rec["failed_rungs"]) == 3
    assert "not an infra failure" in rec["skipped"]


def test_run_attempt_kills_process_group(tmp_path):
    """_run_attempt (the real supervisor mechanism) must reap a hung
    grandchild on timeout — the orphaned-probe scenario."""
    import textwrap
    import time

    marker = str(tmp_path / "grandchild_pid")
    prog = textwrap.dedent(f"""
        import subprocess, sys, time
        subprocess.Popen([sys.executable, "-c",
            "import time, os\\n"
            "open({marker!r}, 'w').write(str(os.getpid()))\\n"
            "time.sleep(600)"])
        time.sleep(600)
    """)
    t0 = time.time()
    rc, out = bench._run_attempt(
        dict(os.environ), tmo=25.0, argv=[sys.executable, "-c", prog]
    )
    assert rc == 124
    # interpreter startup runs the axon sitecustomize (preimports jax,
    # ~5-10s per process, two levels deep) — the 25s budget covers it
    assert os.path.exists(marker), "grandchild never started within budget"
    gpid = int(open(marker).read())
    deadline = time.time() + 10
    while _proc_state(gpid) not in (None, "Z") and time.time() < deadline:
        time.sleep(0.2)
    assert _proc_state(gpid) in (None, "Z"), (
        f"grandchild {gpid} survived the group kill "
        f"(state={_proc_state(gpid)}, wall={time.time() - t0:.1f}s)"
    )
