"""MoE FFN + expert parallelism (ops/ffn.py MoEFFN, "expert" mesh axis).

Beyond the reference (SURVEY.md §2.5 "EP — absent"): dense dropless top-k
routing, Switch-style load-balance aux loss, expert params sharded one
expert-group per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.ops.ffn import Mlp, MoEFFN
from dinov3_tpu.train import build_train_setup, put_batch

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def test_moe_forward_shape_and_aux():
    x = jax.random.normal(jax.random.key(0), (3, 7, 16))
    moe = MoEFFN(hidden_dim=32, num_experts=4, top_k=2, **F32)
    params = {"params": moe.init(jax.random.key(1), x)["params"]}
    y, aux = moe.apply(params, x, mutable=["losses"])
    assert y.shape == x.shape and bool(jnp.isfinite(y).all())
    (aux_loss,) = jax.tree.leaves(aux["losses"])
    # Switch aux loss is minimized at perfectly uniform routing where it
    # equals top_k (each token selects k experts; sum_e f_e = k)
    assert float(aux_loss) >= 2.0 - 1e-3


def test_moe_topk_equals_experts_is_dense_mixture():
    """top_k == E: gate = softmax probs, output = prob-weighted expert mix.
    Check against a manual per-expert computation."""
    x = jax.random.normal(jax.random.key(0), (2, 5, 8))
    moe = MoEFFN(hidden_dim=16, num_experts=3, top_k=3, act=lambda t: t, **F32)
    import flax.linen as nn

    params = nn.meta.unbox(moe.init(jax.random.key(1), x))["params"]
    y = moe.apply({"params": params}, x)

    probs = jax.nn.softmax(
        np.asarray(x) @ np.asarray(params["router"]["kernel"]), axis=-1
    )
    manual = np.zeros_like(np.asarray(x))
    for e in range(3):
        h = np.asarray(x) @ np.asarray(params["w1"][e]) + np.asarray(params["b1"][e])
        o = h @ np.asarray(params["w2"][e]) + np.asarray(params["b2"][e])
        manual += probs[..., e:e + 1] * o
    np.testing.assert_allclose(np.asarray(y), manual, atol=1e-4)


def test_moe_topk_sparsity():
    """top_k=1: each token's output is exactly one expert's output."""
    x = jax.random.normal(jax.random.key(0), (1, 4, 8))
    moe = MoEFFN(hidden_dim=16, num_experts=4, top_k=1, act=lambda t: t, **F32)
    import flax.linen as nn

    params = nn.meta.unbox(moe.init(jax.random.key(1), x))["params"]
    y = np.asarray(moe.apply({"params": params}, x))
    probs = jax.nn.softmax(
        np.asarray(x) @ np.asarray(params["router"]["kernel"]), axis=-1
    )
    chosen = np.argmax(probs, axis=-1)
    for b in range(1):
        for t in range(4):
            e = chosen[b, t]
            h = np.asarray(x[b, t]) @ np.asarray(params["w1"][e]) + np.asarray(params["b1"][e])
            o = h @ np.asarray(params["w2"][e]) + np.asarray(params["b2"][e])
            np.testing.assert_allclose(y[b, t], o, atol=1e-4)


SMOL_MOE = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "student.ffn_layer=moe", "student.moe_num_experts=2",
    "student.moe_top_k=1",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=32", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=32", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def test_moe_train_step_expert_parallel(eight_devices):
    """Full SSL step with MoE blocks under (data, fsdp, expert) sharding:
    expert params land sharded over the expert axis, losses include the
    aux term, loss finite over two steps."""
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL_MOE + [
        "parallel.data=2", "parallel.fsdp=2", "parallel.expert=2",
        "parallel.zero3=false",
    ])
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.mesh.shape["expert"] == 2

    # expert-stacked ffn params sharded over the expert axis
    blk0 = setup.state_shardings.params["student"]["backbone"]["blocks_0"]["mlp"]
    def has_expert(s):
        return any(
            "expert" in (ax if isinstance(ax, tuple) else (ax,))
            for ax in s.spec if ax is not None
        )
    expert_leaves = [s for k, s in blk0.items() if k in ("w1", "w2", "b1", "b2")]
    assert expert_leaves and all(has_expert(s) for s in expert_leaves), blk0

    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert "moe_aux_loss" in metrics
    assert np.isfinite(float(metrics["total_loss"]))
    state, metrics = setup.step_fn(
        state, dbatch, setup.scalars(1), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))


def test_moe_scan_layers_train_step(eight_devices):
    """MoE composes with nn.scan over blocks: the aux loss rides the
    "losses" collection through the scan (variable_axes) — VERDICT r2 #5
    deleted the NotImplementedError guard."""
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL_MOE + [
        "train.scan_layers=true", "parallel.data=-1",
    ])
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert "moe_aux_loss" in metrics
    aux = float(metrics["moe_aux_loss"])
    # Switch aux = E * sum_e f_e p_e is ~1 at balance, <= E always
    assert 0.5 <= aux <= 2.1, aux
    assert np.isfinite(float(metrics["total_loss"]))


def test_moe_pipeline_train_step(eight_devices):
    """MoE composes with the GPipe pipeline: per-tick sown aux losses are
    stacked by the tick scan and bubble slots are masked out of the mean
    (ssl_meta_arch._apply_backbone)."""
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL_MOE + [
        "parallel.data=-1", "parallel.pipe=2",
    ])
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.mesh.shape["pipe"] == 2
    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert "moe_aux_loss" in metrics
    aux = float(metrics["moe_aux_loss"])
    assert 0.5 <= aux <= 2.1, aux
    assert np.isfinite(float(metrics["total_loss"]))
    state, metrics = setup.step_fn(
        state, dbatch, setup.scalars(1), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))
