"""Low-precision training arms (train.low_precision: ops/lowp.py +
train/setup.py wiring + the lowp flax collection through the block
stack) vs the bf16 default.

The fp8/int8 arms quantize the attn/mlp block matmul KERNELS
per-tensor with delayed scaling (amax-history rings in the train
state, advanced after the optimizer/EMA update) and ride the ZeRO-3
in-loop weight stream with 1-byte codes; masters, Adam moments,
norms/biases and the EMA teacher storage stay untouched. These tests
pin:

- the delayed-scaling state math (symmetric scale/quantize, history
  ring init/roll, the scale-site remap of Dense kernels);
- the bf16 default arm as a BITWISE no-op: an explicit
  ``arm=bf16`` config (with a non-default ring length it must ignore)
  produces the identical program — losses and post-step params equal
  to the config without any low_precision overrides;
- multi-step loss trajectories tracking bf16 within the documented
  tolerance (fp8 on the dp x fsdp zero3 mesh; int8 dp-only under
  ``slow`` — int8 also executes in the committed COST_LP_r21.json run
  and CI's ``cost_lowp.py --smoke``), with live amax rings and the
  setup drift probe under ``train.low_precision.divergence_tol``;
- the streamed-gather census: identical ``zero3_stream`` collective
  counts across arms, >= 1.8x fewer streamed bytes on the quantized
  arm, zero unattributed collectives, and the ``lowp_dequant``
  epilogue stamped into the quantized program only;
- cross-arm checkpoints: a bf16 checkpoint restored into an fp8 run
  reseeds fresh rings from the RESTORED masters; fp8 -> fp8 restores
  rings bitwise; an fp8 checkpoint restores into a bf16 run with the
  rings ignored;
- the ``warn_lowp_divergence`` guardrail (fire/silent), the arm
  conflict raises (fp8_enabled / moe / pipe>1 / convnext / typo'd
  arm), the no-silent-knobs census registration, the serve-quant
  numerics staying bitwise after delegating to ops/lowp.py, and the
  committed COST_LP_r21.json acceptance numbers.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
]
MESH = ["parallel.data=2", "parallel.fsdp=4", "parallel.zero3=true"]
# documented per-step relative loss-trajectory band of the quantized
# arms vs bf16 at the SMOL scale (COST_LP_r21.json measures 0.6%/1.4%
# at 8 steps; 5% is the alerting band)
LOSS_RTOL = 0.05


def _setup(extra, batch_size, devices):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=devices), batch


def _flat(tree):
    return jtu.tree_flatten_with_path(tree)[0]


def assert_trees_bitwise(a, b, what, limit=None):
    fa, fb = _flat(a), _flat(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in (zip(fa, fb) if limit is None
                              else zip(fa[:limit], fb[:limit])):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: {jtu.keystr(pa)} differs")


def _run(setup, batch, n_steps):
    from dinov3_tpu.train import put_batch

    d = put_batch(batch, setup.batch_shardings)
    state, losses = setup.state, []
    for i in range(n_steps):
        state, m = setup.step_fn(state, d, setup.scalars(i),
                                 jax.random.key(0))
        losses.append(float(m["total_loss"]))
    return state, losses


@pytest.fixture(scope="module")
def arms(eight_devices):
    """One setup + 2 executed steps per precision arm on the dp x fsdp
    zero3 mesh — shared by the trajectory / census / checkpoint tests.
    The fast set runs the bf16 control + the fp8 treatment only (each
    arm is a full setup + compile, real wall-clock on this suite); the
    int8 arm executes in the slow dp-only test below, in the committed
    COST_LP_r21.json acceptance, and in CI's `cost_lowp.py --smoke`."""
    out = {}
    for arm, extra in (("bf16", []),
                       ("fp8", ["train.low_precision.arm=fp8"])):
        setup, batch = _setup(MESH + extra, 8, eight_devices)
        final, losses = _run(setup, batch, 2)
        out[arm] = {"setup": setup, "batch": batch,
                    "final": final, "losses": losses}
    return out


# ---------------- delayed-scaling state math ----------------

def test_symmetric_scale_and_quantize_math():
    from dinov3_tpu.ops.lowp import (
        qspec,
        scale_from_history,
        symmetric_quantize,
        symmetric_scale,
    )

    # zero amax -> scale 1.0 (exact divide, dequant returns exact zeros)
    assert float(symmetric_scale(jnp.float32(0.0), 127.0)) == 1.0
    assert float(symmetric_scale(jnp.float32(254.0), 127.0)) == 2.0
    # fp8 e4m3 qmax is 448, int8 is 127, and their accumulators
    assert qspec("fp8").qmax == 448.0
    assert qspec("fp8").acc_dtype == jnp.float32
    assert qspec("int8").qmax == 127.0
    assert qspec("int8").acc_dtype == jnp.int32
    # int8 codes: round-half-to-even then clip to the symmetric range
    q = symmetric_quantize(
        jnp.float32([2.5, -2.5, 3.5, 300.0]), jnp.float32(1.0), 127,
        jnp.int8)
    assert q.dtype == jnp.int8
    assert q.tolist() == [2, -2, 4, 127]
    # fp8 codes: no integer rounding, straight cast into e4m3
    qf = symmetric_quantize(
        jnp.float32([1.0, -448.0]), jnp.float32(1.0), 448.0,
        jnp.float8_e4m3fn)
    assert qf.dtype == jnp.float8_e4m3fn
    assert qf.astype(jnp.float32).tolist() == [1.0, -448.0]
    # delayed scale: margin * max(history) / qmax
    hist = jnp.float32([1.0, 254.0, 2.0])
    assert float(scale_from_history(hist, 127.0, 1.0)) == 2.0
    assert float(scale_from_history(hist, 127.0, 2.0)) == 4.0
    # all-zero history degrades to the safe 1.0
    assert float(scale_from_history(jnp.zeros(4), 127.0, 1.0)) == 1.0


def test_kernel_path_and_scale_site():
    from dinov3_tpu.ops.lowp import lowp_kernel_path, lowp_scale_site

    def path(*keys):
        return tuple(jtu.DictKey(k) for k in keys)

    # attn/mlp matmul kernels quantize; their biases ride the bf16
    # stream; norms and the router were never castable
    assert lowp_kernel_path(path("blocks", "attn", "qkv_kernel"))
    assert lowp_kernel_path(path("blocks", "mlp", "fc1", "kernel"))
    assert not lowp_kernel_path(path("blocks", "attn", "qkv_bias"))
    assert not lowp_kernel_path(path("blocks", "norm1", "scale"))
    assert not lowp_kernel_path(path("blocks", "mlp", "router", "kernel"))
    assert not lowp_kernel_path(path("patch_embed", "kernel"))
    # Dense kernels fold into the parent module's collection slot;
    # direct attn kernels keep their name in place
    assert lowp_scale_site(path("blocks", "mlp", "fc1", "kernel")) == (
        ("blocks", "mlp"), "fc1_kernel")
    assert lowp_scale_site(path("blocks", "attn", "qkv_kernel")) == (
        ("blocks", "attn"), "qkv_kernel")


def test_history_init_and_ring_roll():
    from dinov3_tpu.ops.lowp import (
        lowp_amax_tree,
        lowp_history_init,
        lowp_history_step,
    )

    params = {
        # scanned stack: [L, in, out] kernels reduce to per-layer [L]
        "blocks": {"attn": {"qkv_kernel": jnp.float32(
            np.arange(2 * 3 * 6).reshape(2, 3, 6) - 10.0)}},
        # unrolled kernel reduces to a scalar
        "head": {"mlp": {"fc1": {"kernel": jnp.float32([[1.0, -7.0]])}}},
        # non-kernel leaves never enter the tree
        "norm": {"scale": jnp.ones((4,))},
    }
    amax = lowp_amax_tree(params)
    assert amax["blocks"]["attn"]["qkv_kernel"].shape == (2,)
    assert float(amax["head"]["mlp"]["fc1_kernel"]) == 7.0
    assert "norm" not in amax
    # init fills EVERY slot with the current amax (not zeros)
    hist = lowp_history_init(params, 4)
    h = hist["blocks"]["attn"]["qkv_kernel"]
    assert h.shape == (2, 4) and h.dtype == jnp.float32
    assert np.array_equal(np.asarray(h), np.asarray(
        jnp.broadcast_to(amax["blocks"]["attn"]["qkv_kernel"][:, None],
                         (2, 4))))
    # the roll drops the oldest slot and appends the NEW masters' amax
    new_params = jax.tree.map(lambda x: x * 2.0, params)
    rolled = lowp_history_step(hist, new_params)
    r = np.asarray(rolled["head"]["mlp"]["fc1_kernel"])
    assert r.shape == (4,)
    assert r.tolist() == [7.0, 7.0, 7.0, 14.0]


# ---------------- the bf16 arm is bitwise inert ----------------

def test_bf16_arm_bitwise_noop(arms, eight_devices):
    """An explicit ``arm=bf16`` config — including a non-default ring
    length the bf16 arm must ignore — runs the identical program: no
    rings, no drift probe, losses and post-step params bitwise equal
    to the config without any low_precision overrides."""
    base = arms["bf16"]
    assert base["setup"].lowp_arm == "bf16"
    assert base["setup"].lowp_drift is None
    assert base["setup"].state.lowp is None
    setup, batch = _setup(
        MESH + ["train.low_precision.arm=bf16",
                "train.low_precision.amax_history_len=4"],
        8, eight_devices)
    assert setup.state.lowp is None
    final, losses = _run(setup, batch, 2)
    assert losses == base["losses"]
    assert_trees_bitwise(final.params, base["final"].params,
                         "bf16-arm params", limit=32)


# ---------------- quantized trajectories + state ----------------

def test_lowp_trajectories_dp_fsdp(arms):
    """fp8 on the dp x fsdp zero3 mesh: live amax rings advanced per
    step, setup drift probe under the tolerance gate, and the loss
    trajectory inside the documented band around bf16."""
    from dinov3_tpu.ops.lowp import lowp_amax_tree

    bf16 = arms["bf16"]["losses"]
    for name in ("fp8",):
        setup, final = arms[name]["setup"], arms[name]["final"]
        assert setup.lowp_arm == name
        # the drift probe ran at setup and sits under the gate
        assert setup.lowp_drift is not None
        assert 0.0 < setup.lowp_drift["max"] < 0.2
        # rings live in the train state and advanced with the masters:
        # the newest slot is the CURRENT (post-update) masters' amax
        assert final.lowp is not None
        for k in ("student", "teacher"):
            want = lowp_amax_tree(final.params[k]["backbone"])
            got_last = jax.tree.map(lambda h: h[..., -1], final.lowp[k])
            assert_trees_bitwise(got_last, want, f"{name} {k} ring amax")
        rel = [abs(a - b) / abs(b)
               for a, b in zip(arms[name]["losses"], bf16)]
        assert all(np.isfinite(r) for r in rel)
        assert max(rel) < LOSS_RTOL, (name, rel)


@pytest.mark.slow
def test_lowp_trajectory_dp_only(eight_devices):
    """The int8 arm on a pure-dp zero3 mesh (no fsdp axis): same
    trajectory band — the code gathers ride whatever zero3 stream the
    mesh shape produces."""
    s_b, batch = _setup(["parallel.data=8", "parallel.zero3=true"],
                        16, eight_devices)
    s_q, _ = _setup(["parallel.data=8", "parallel.zero3=true",
                     "train.low_precision.arm=int8"], 16, eight_devices)
    _, l_b = _run(s_b, batch, 2)
    _, l_q = _run(s_q, batch, 2)
    rel = [abs(a - b) / abs(b) for a, b in zip(l_q, l_b)]
    assert max(rel) < LOSS_RTOL, rel


# ---------------- streamed-gather census ----------------

def test_streamed_gather_census(arms):
    """The quantized arm's compiled step: identical zero3_stream
    collective COUNTS vs bf16, >= 1.8x fewer streamed BYTES (1-byte
    codes vs the bf16 stream), zero unattributed collectives, and the
    lowp_dequant epilogue stamped into the quantized program only."""
    from dinov3_tpu.train import put_batch
    from dinov3_tpu.utils import hlo_collective_census

    def compiled_text(rec):
        setup = rec["setup"]
        d = put_batch(rec["batch"], setup.batch_shardings)
        return setup.step_fn.lower(
            setup.state, d, setup.scalars(0), jax.random.key(0)
        ).compile().as_text()

    txt_b = compiled_text(arms["bf16"])
    txt_q = compiled_text(arms["fp8"])
    cen_b = hlo_collective_census(txt_b)
    cen_q = hlo_collective_census(txt_q)
    assert cen_b["unattributed"] == 0 and cen_q["unattributed"] == 0
    sb = cen_b["by_scope"]["zero3_stream"]
    sq = cen_q["by_scope"]["zero3_stream"]
    assert sq["ops"] == sb["ops"] > 0
    assert sb["bytes"] / sq["bytes"] >= 1.8, (sb, sq)
    # engagement: the dequant epilogue exists ONLY in the quantized arm
    assert "lowp_dequant" in txt_q
    assert "lowp_dequant" not in txt_b
    assert "lowp_amax" in txt_q


# ---------------- cross-arm checkpoints ----------------

def test_cross_arm_checkpoint(tmp_path, arms):
    """bf16 -> fp8 reseeds fresh rings from the RESTORED masters;
    fp8 -> fp8 restores the rings bitwise; fp8 -> bf16 ignores them."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.ops.lowp import lowp_history_init
    from dinov3_tpu.train import put_batch

    s_b, s_q = arms["bf16"]["setup"], arms["fp8"]["setup"]
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, arms["bf16"]["final"])        # no rings in this one
    ck.save(2, arms["fp8"]["final"])         # live rings in this one
    ck.wait_until_finished()

    # bf16 checkpoint into an fp8 run: masters restore bitwise and the
    # rings reseed from THOSE masters (every slot the restored amax)
    restored = ck.restore(s_q.state, 1)
    assert_trees_bitwise(restored.params, arms["bf16"]["final"].params,
                         "bf16 -> fp8 params", limit=32)
    assert restored.lowp is not None
    H = int(jax.tree.leaves(s_q.state.lowp)[0].shape[-1])
    for k in ("student", "teacher"):
        want = lowp_history_init(restored.params[k]["backbone"], H)
        assert_trees_bitwise(restored.lowp[k], want,
                             f"reseeded {k} rings")
    d = put_batch(arms["fp8"]["batch"], s_q.batch_shardings)
    st, m = s_q.step_fn(restored, d, s_q.scalars(1), jax.random.key(0))
    assert np.isfinite(float(m["total_loss"]))

    # fp8 checkpoint back into an fp8 run: rings round-trip bitwise
    same = ck.restore(s_q.state, 2)
    assert_trees_bitwise(same.lowp, arms["fp8"]["final"].lowp,
                         "fp8 -> fp8 rings")

    # fp8 checkpoint into a bf16 run: rings ignored, masters bitwise
    back = ck.restore(s_b.state, 2)
    assert back.lowp is None
    assert_trees_bitwise(back.params, arms["fp8"]["final"].params,
                         "fp8 -> bf16 params", limit=32)


# ---------------- guardrail / conflicts / registration ----------------

def test_warn_lowp_divergence_fire_and_silent():
    from dinov3_tpu.configs.config import warn_lowp_divergence

    with pytest.warns(UserWarning, match="lowp divergence axis"):
        msg = warn_lowp_divergence(0.5, tol=0.2, axis="unit test")
    assert msg is not None and "unit test" in msg
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert warn_lowp_divergence(0.01, tol=0.2) is None
    assert not caught


def test_arm_conflicts_raise(eight_devices):
    from dinov3_tpu.configs.config import lowp_cfg
    from dinov3_tpu.models import build_backbone

    # a typo'd arm must never silently train bf16
    cfg = get_default_config()
    apply_dot_overrides(cfg, ["train.low_precision.arm=fp16"])
    with pytest.raises(ValueError, match="low_precision.arm"):
        lowp_cfg(cfg)
    # the legacy fp8 hook and the lowp arms would quantize the same
    # matmuls; moe experts are not stream-castable Dense kernels; the
    # pipelined stack bypasses the per-block stream; convnext has no
    # block kernels at all
    for extra, match in (
        (["student.fp8_enabled=true"], "fp8_enabled"),
        (["student.ffn_layer=moe", "student.moe_num_experts=2"], "moe"),
        (["parallel.pipe=2"], "pipe"),
    ):
        with pytest.raises(ValueError, match=match):
            _setup(["train.low_precision.arm=fp8"] + extra, 16,
                   eight_devices)
    cfg = get_default_config()
    apply_dot_overrides(
        cfg, ["student.arch=convnext_tiny",
              "train.low_precision.arm=int8"])
    with pytest.raises(ValueError, match="ViT backbone"):
        build_backbone(cfg)


def test_census_registration():
    """The no-silent-knobs census covers the train.low_precision block:
    all four knobs registered with justifications, census green."""
    from dinov3_tpu.tuning.census import knob_census

    census = knob_census()
    assert census["ok"], (census["unregistered"], census["stale_registry"])
    justified = set(census["by_kind"]["justified"])
    for knob in ("train.low_precision.arm",
                 "train.low_precision.amax_history_len",
                 "train.low_precision.scale_margin",
                 "train.low_precision.divergence_tol"):
        assert knob in justified, knob


def test_serve_quant_numerics_unchanged():
    """serve/quant.py delegates its scale/round/clip math to
    ops/lowp.py — the (q, scale) pair must stay bitwise what the
    pre-refactor numpy expressions produced."""
    from dinov3_tpu.serve.quant import quantize_leaf

    w = np.random.default_rng(0).standard_normal((16, 8)).astype(
        np.float32) * 0.02
    w[:, 3] = 0.0  # a zero output channel exercises the scale-1.0 path
    leaf = quantize_leaf(w)
    amax = np.max(np.abs(w), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    assert np.array_equal(np.asarray(leaf.q), q)
    assert np.array_equal(np.asarray(leaf.scale), scale)
    assert np.all(np.asarray(leaf.q)[:, 3] == 0)


# ---------------- committed artifact ----------------

def test_cost_lp_artifact_acceptance():
    """COST_LP_r21.json: streamed bytes down >= 1.8x at identical
    stream counts, unattributed collectives AND unattributed trace ms
    pinned 0, trajectories inside the documented band, bf16 bitwise
    control, drift probes under the gate."""
    with open(os.path.join(REPO, "COST_LP_r21.json")) as f:
        rec = json.load(f)
    assert rec["bf16_bitwise_control"] is True
    ops = rec["stream_ops"]
    assert ops["fp8"] == ops["int8"] == ops["bf16"] > 0
    for arm in ("fp8", "int8"):
        assert rec["stream_bytes"]["bf16"] / rec["stream_bytes"][arm] >= 1.8
        assert rec["trajectory_rel_max"][arm] < rec["loss_rtol_bound"]
        a = rec["arms"][arm]
        assert a["unattributed"] == 0
        assert a["anatomy"]["unattributed_collective_ms"] == 0
        assert a["lowp_dequant_scope_lines"] > 0
        assert a["drift_probe"]["max"] < rec["divergence_tol"]
    assert rec["arms"]["bf16"]["lowp_dequant_scope_lines"] == 0
    assert rec["arms"]["bf16"]["unattributed"] == 0
