"""Continuous-packing serve engine (serve/): batcher, packed forward,
weights, guardrails, and the committed SERVE_r14.json acceptance.

Pins:

- batcher mechanics: FFD row assignment (budget + extraction-slot
  caps, leftover requests queued in arrival order), flush policy
  (budget full / oldest-waited deadline), plane assembly (segment ids,
  prefix indices, CLS landing sites, patchify/coords parity with the
  ops/ twins), oversize admission rejection;
- feature equivalence: the ONE ahead-of-time-compiled packed forward
  reproduces the per-image oracle's CLS + pooled-patch features on
  ragged traffic, while its compile count stays pinned at 1 (the
  oracle's grows with shape diversity — the pathology under test);
- serving weights: checkpoints from all FOUR opt-state arms
  (replicated / PR-5 flat / PR-9 bucketed / PR-7 zero3) resolve to
  ONE bitwise-identical bf16 serving tree, and the bf16 cast is
  deterministic + idempotent;
- the evals/features.py ragged-tail fix: a partial final batch runs
  through the same compiled program (compile count 1), padded rows
  sliced off, and the serve-engine extraction path returns the same
  features;
- the warn_serve_pad_waste guardrail (axis-labelled fire/silent) and
  the serve copy-census category;
- the committed SERVE_r14.json: packed >= 2x the rectangular oracle's
  sustained img/s on the mixed ragged mix at equal features, p50/p99
  for every mix, exactly 1 packed compile, zero unattributed
  collectives.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.serve import (
    ContinuousBatcher,
    OracleServeEngine,
    PackedServeEngine,
    ServeLayout,
    ServeRequest,
    cast_serving_tree,
    load_serving_model,
    patch_coords_np,
    patchify,
    serve_layout_from_cfg,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
]

SERVE_SMOL = SMOL + [
    "serve.min_px=8", "serve.max_px=24", "serve.rows=3",
    "serve.row_tokens=40", "serve.max_segments_per_row=6",
]


def _layout(**kw) -> ServeLayout:
    base = dict(rows=2, row_tokens=20, n_prefix=1, max_segments_per_row=3,
                patch_size=4, min_px=8, max_px=16)
    base.update(kw)
    return ServeLayout(**base)


def _req(rid, h, w, arrival=0.0, rng=None):
    img = (rng.standard_normal((h, w, 3)).astype(np.float32)
           if rng is not None else np.zeros((h, w, 3), np.float32))
    return ServeRequest(request_id=rid, image=img, arrival_s=arrival)


@pytest.fixture(scope="module")
def tiny_serve():
    """One vit_test serving model + bf16 params + layout for the file."""
    import flax.linen as nn

    from dinov3_tpu.models import build_backbone

    cfg = get_default_config()
    apply_dot_overrides(cfg, SERVE_SMOL)
    model = build_backbone(cfg, teacher=True)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    params = cast_serving_tree(params)
    return cfg, model, params, serve_layout_from_cfg(cfg)


# ---------------- batcher unit tests ----------------

def test_layout_seq_len_budget_and_oversize():
    L = _layout()
    assert L.token_budget == 40
    assert L.seq_len(8, 8) == 1 + 4          # 2x2 patches
    assert L.seq_len(16, 12) == 1 + 4 * 3
    with pytest.raises(ValueError):
        L.seq_len(10, 8)                      # not patch-divisible
    b = ContinuousBatcher(L)
    with pytest.raises(ValueError, match="row budget"):
        b.admit(_req(0, 24, 16))              # 25 tokens > row_tokens 20


def test_patchify_and_coords_match_ops_twins():
    rng = np.random.default_rng(0)
    img = rng.standard_normal((12, 8, 3)).astype(np.float32)
    pats = patchify(img, 4)
    assert pats.shape == (6, 4, 4, 3)
    # same patch order + inner layout as PatchEmbed's reshape
    ref = img.reshape(3, 4, 2, 4, 3).transpose(0, 2, 1, 3, 4)
    assert np.array_equal(pats, ref.reshape(6, 4, 4, 3))
    # bitwise f32 parity with ops/rope.patch_coords
    from dinov3_tpu.ops.rope import patch_coords

    for mode in ("separate", "max", "min"):
        want = np.asarray(patch_coords(3, 2, normalize=mode))
        assert np.array_equal(patch_coords_np(3, 2, mode), want), mode


def test_ffd_row_assignment_and_leftovers():
    # row_tokens 20: a 13-token and a 5-token share a row (18), the
    # second 13-token opens row 1, the trailing 5-token first-fits
    # back into row 0; the third 13-token doesn't fit anywhere and
    # stays queued (arrival order preserved)
    L = _layout()
    b = ContinuousBatcher(L)
    for rid, (h, w) in enumerate(
            [(16, 12), (8, 8), (16, 12), (8, 8), (16, 12)]):
        b.admit(_req(rid, h, w))
    plan = b.next_pack()
    by_id = {pl.request.request_id: pl for pl in plan.placements}
    assert sorted(by_id) == [0, 1, 2, 3]
    assert by_id[0].row == 0 and by_id[0].offset == 0
    assert by_id[2].row == 1                  # first-fit: row 0 full at 13+13
    assert by_id[1].row == 0 and by_id[1].offset == 13
    assert by_id[3].row == 1 and by_id[3].offset == 13
    assert plan.tokens_used == 13 + 13 + 5 + 5
    assert plan.pad_waste == pytest.approx(1 - 36 / 40)
    # leftover 13-token request ships in the next pack
    assert b.queue_len == 1
    plan2 = b.next_pack()
    assert [pl.request.request_id for pl in plan2.placements] == [4]
    assert b.next_pack() is None


def test_segment_slot_cap():
    # 5-token images: 4 fit a 20-token row, but max_segments_per_row=3
    # caps occupancy at 3 per row
    L = _layout()
    b = ContinuousBatcher(L)
    for rid in range(8):
        b.admit(_req(rid, 8, 8))
    plan = b.next_pack()
    rows = [pl.row for pl in plan.placements]
    assert len(plan.placements) == 6
    assert rows.count(0) == 3 and rows.count(1) == 3
    assert b.queue_len == 2


def test_flush_policy_budget_and_deadline():
    L = _layout()
    b = ContinuousBatcher(L, flush_ms=10.0)
    assert not b.should_flush(0.0)            # empty queue never flushes
    b.admit(_req(0, 8, 8, arrival=1.0))
    assert not b.should_flush(1.005)          # 5ms < deadline, budget free
    assert b.should_flush(1.010)              # oldest waited 10ms
    assert b.flush_deadline() == pytest.approx(1.010)
    for rid in range(1, 8):
        b.admit(_req(rid, 8, 8, arrival=1.0))
    assert b.queued_tokens == 40
    assert b.should_flush(1.0)                # budget full, no wait needed


def test_plane_assembly():
    rng = np.random.default_rng(1)
    L = _layout()
    b = ContinuousBatcher(L)
    b.admit(_req(0, 16, 12, rng=rng))         # 13 tokens, row 0
    b.admit(_req(1, 8, 8, rng=rng))           # 5 tokens, row 0 @ 13
    plan = b.next_pack()
    pl0, pl1 = sorted(plan.placements, key=lambda p: p.request.request_id)
    seg, pidx = plan.planes["seg"], plan.planes["prefix_idx"]
    assert list(seg[0, :18]) == [0] * 13 + [1] * 5
    assert list(seg[0, 18:]) == [-1] * 2 and np.all(seg[1] == -1)
    assert pidx[0, 0] == 0 and pidx[0, 13] == 0   # CLS at each offset
    assert np.all(pidx[0, 1:13] == -1)
    assert plan.planes["cls_index"][0, 0] == 0
    assert plan.planes["cls_index"][0, 1] == 13
    assert np.array_equal(
        plan.planes["patches"][0, 1:13], patchify(pl0.request.image, 4))
    assert np.array_equal(
        plan.planes["coords"][0, 14:18], patch_coords_np(2, 2))
    # pad slots stay zeroed
    assert not plan.planes["patches"][0, 18:].any()
    assert not plan.planes["patches"][1].any()


# ---------------- packed forward vs oracle ----------------

def test_packed_features_match_oracle_single_compile(tiny_serve):
    """Ragged traffic through the packed engine: CLS + pooled features
    match the per-image oracle within bf16-compute tolerance, packed
    compile count stays 1 while the oracle's grows per shape."""
    cfg, model, params, layout = tiny_serve
    rng = np.random.default_rng(2)
    eng = PackedServeEngine(model, params, layout, warn=False)
    ora = OracleServeEngine(model, params, layout, mode="per_image")
    sizes = [(8, 8), (16, 16), (12, 8), (24, 16), (8, 12), (16, 24),
             (20, 20)]
    images = [rng.standard_normal((h, w, 3)).astype(np.float32)
              for h, w in sizes]
    for e in (eng, ora):
        for i, im in enumerate(images):
            e.submit(im, request_id=i)
    packed, oracle = [], []
    while eng.queue_len:
        packed.extend(eng.flush())
    oracle.extend(ora.flush())
    assert len(packed) == len(oracle) == len(images)
    by_id = {r.request_id: r for r in oracle}
    for r in packed:
        o = by_id[r.request_id]
        assert r.n_patches == o.n_patches
        np.testing.assert_allclose(
            r.cls_feature, o.cls_feature, atol=1e-5,
            err_msg=f"cls, request {r.request_id}")
        np.testing.assert_allclose(
            r.pooled_patch_feature, o.pooled_patch_feature, atol=1e-5,
            err_msg=f"pooled, request {r.request_id}")
    assert eng.compile_count == 1
    assert eng.packs_run >= 2                 # traffic spanned packs
    assert ora.compile_count == len(set(sizes))


def test_packed_census_serve_attribution(tiny_serve):
    """The one packed program's HLO: serve-scoped copies classified to
    the "serve" category, zero unattributed collectives."""
    from dinov3_tpu.utils import (
        classify_copy,
        hlo_collective_census,
        hlo_copy_census,
    )

    cfg, model, params, layout = tiny_serve
    eng = PackedServeEngine(model, params, layout, warn=False)
    hlo = eng.compiled_text()
    census = hlo_copy_census(hlo)
    assert hlo_collective_census(hlo)["unattributed"] == 0
    # the classifier routes every serve scope; only categories the
    # census knows appear
    assert classify_copy("  %x = f32[4]{0} copy(a), metadata={op_name="
                         "\"jit/serve_pack/reshape\"}") == "serve"
    assert classify_copy("  %x = f32[4]{0} copy(a), metadata={op_name="
                         "\"jit/serve_ring/dus\"}") == "serve"
    known = {"donation_async", "gather_pack", "update_shard", "telemetry",
             "zero3", "bucket", "serve", "rng", "small", "large"}
    assert set(census["by_category"]) <= known


def test_build_serve_engine_dispatch(tiny_serve):
    """continuous_packing=false routes to the configured oracle arm."""
    from dinov3_tpu.configs.config import continuous_packing_wished
    from dinov3_tpu.serve import build_serve_engine

    cfg, model, params, layout = tiny_serve
    ocfg = get_default_config()
    apply_dot_overrides(ocfg, SERVE_SMOL + [
        "serve.continuous_packing=false", "serve.oracle=per_image"])
    assert continuous_packing_wished(cfg)
    assert not continuous_packing_wished(ocfg)
    eng = build_serve_engine(ocfg, params=params, warn=False)
    assert isinstance(eng, OracleServeEngine) and eng.mode == "per_image"


# ---------------- serving weights: the four arms ----------------

def test_serving_tree_from_all_four_arms(tmp_path, eight_devices):
    """One training step per opt-state arm from the same init, one
    checkpoint each; load_serving_model resolves every one of them to
    the SAME bf16 serving tree bitwise (the params tree is model-shaped
    in all four arms — only the adam moments' layout differs)."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    arms = {
        "replicated": ["parallel.zero3=false", "optim.sharded_update=false",
                       "optim.bucketed_collectives=false"],
        "flat": ["parallel.zero3=false", "optim.bucketed_collectives=false"],
        "bucketed": ["parallel.zero3=false",
                     "optim.bucketed_collectives=true"],
        "zero3": ["parallel.zero3=true"],
    }
    trees = {}
    for name, extra in arms.items():
        cfg = get_default_config()
        apply_dot_overrides(cfg, SMOL + extra)
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 16, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        state, _ = setup.step_fn(
            setup.state, put_batch(batch, setup.batch_shardings),
            setup.scalars(0), jax.random.key(0))
        ck = Checkpointer(str(tmp_path / name), async_save=False,
                          bucket_plan=getattr(setup, "bucket_plan", None))
        ck.save(1, state)
        ck.wait_until_finished()
        ck.close()

        ecfg = get_default_config()
        apply_dot_overrides(ecfg, SMOL)
        _, tree = load_serving_model(ecfg, str(tmp_path / name))
        trees[name] = tree

    flat = {n: jtu.tree_flatten_with_path(t)[0] for n, t in trees.items()}
    ref = flat["replicated"]
    for name in ("flat", "bucketed", "zero3"):
        assert len(flat[name]) == len(ref)
        for (path, a), (_, b) in zip(ref, flat[name]):
            assert a.dtype == b.dtype
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"replicated vs {name}: {jtu.keystr(path)}")
    floats = [l for _, l in ref if jnp.issubdtype(l.dtype, jnp.floating)]
    assert floats and all(l.dtype == jnp.bfloat16 for l in floats)

    # int8 quantization is a pure function of the serving tree, so the
    # four arms must also quantize identically — bitwise q AND scale
    # (the fleet's weights fingerprint keys the feature cache on this)
    from dinov3_tpu.serve import (
        QuantLeaf,
        quantize_serving_tree,
        weights_fingerprint,
    )

    qtrees = {n: quantize_serving_tree(t) for n, t in trees.items()}
    qflat = {n: jtu.tree_flatten_with_path(
        t, is_leaf=lambda x: isinstance(x, QuantLeaf))[0]
        for n, t in qtrees.items()}
    qref = qflat["replicated"]
    assert any(isinstance(l, QuantLeaf) for _, l in qref)
    for name in ("flat", "bucketed", "zero3"):
        for (path, a), (_, b) in zip(qref, qflat[name]):
            if isinstance(a, QuantLeaf):
                assert np.array_equal(np.asarray(a.q), np.asarray(b.q)), (
                    f"replicated vs {name}: {jtu.keystr(path)} q")
                assert np.array_equal(np.asarray(a.scale),
                                      np.asarray(b.scale)), (
                    f"replicated vs {name}: {jtu.keystr(path)} scale")
    fps = {weights_fingerprint(t) for t in qtrees.values()}
    assert len(fps) == 1


def test_cast_serving_tree_deterministic(tiny_serve):
    cfg, model, params, _ = tiny_serve
    # params already bf16: idempotent bitwise
    again = cast_serving_tree(params)
    for (p, a), (_, b) in zip(jtu.tree_flatten_with_path(params)[0],
                              jtu.tree_flatten_with_path(again)[0]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), jtu.keystr(p)
    # two independent casts of the same f32 leaf agree bitwise, ints
    # pass through untouched
    leaf = np.float32([1.0000153, -2.5000305, 3.141592653])
    tree = {"w": jnp.asarray(leaf), "n": jnp.asarray([3], jnp.int32)}
    c1, c2 = cast_serving_tree(tree), cast_serving_tree(tree)
    assert c1["w"].dtype == jnp.bfloat16
    assert np.array_equal(np.asarray(c1["w"]), np.asarray(c2["w"]))
    assert c1["n"].dtype == jnp.int32
    assert np.array_equal(np.asarray(c1["n"]), np.asarray(tree["n"]))


# ---------------- evals/features.py: ragged tail + serve path ----------------

def test_features_ragged_tail_single_compile(tiny_serve):
    from dinov3_tpu.evals.features import extract_features, make_feature_fn

    cfg, model, params, _ = tiny_serve
    rng = np.random.default_rng(3)
    full = rng.standard_normal((10, 16, 16, 3)).astype(np.float32)
    labels = np.arange(10)

    def batches(bs):
        for i in range(0, 10, bs):
            yield {"image": full[i:i + bs], "label": labels[i:i + bs]}

    feat = make_feature_fn(model, params)
    # 4 + 4 + 2: the ragged tail pads to 4 rows, same program
    feats, labs = extract_features(model, params, batches(4), feat=feat)
    assert feats.shape == (10, model.embed_dim)
    assert np.array_equal(labs, labels)
    assert feat._cache_size() == 1   # the 2-row tail reused the [4,...] program
    # values match the one-shot full batch (pad rows sliced; rows are
    # independent through the network up to vectorization reassociation)
    want = np.asarray(feat(jnp.asarray(full)))
    np.testing.assert_allclose(feats, want, atol=1e-5)


def test_extract_features_serve_rides_engine(tiny_serve):
    from dinov3_tpu.evals.features import extract_features_serve

    cfg, model, params, layout = tiny_serve
    rng = np.random.default_rng(4)
    sizes = [(8, 8), (16, 16), (12, 16), (24, 24)]
    images = [rng.standard_normal((h, w, 3)).astype(np.float32)
              for h, w in sizes]
    eng = PackedServeEngine(model, params, layout, warn=False)
    feats, labs = extract_features_serve(eng, iter(images), iter([7, 8, 9, 10]))
    assert feats.shape == (4, model.embed_dim)
    assert list(labs) == [7, 8, 9, 10]
    assert eng.compile_count == 1
    # submission order preserved: request i is image i
    ora = OracleServeEngine(model, params, layout, mode="per_image")
    for i, im in enumerate(images):
        ora.submit(im, request_id=i)
    want = {r.request_id: r.cls_feature for r in ora.flush()}
    for i in range(4):
        np.testing.assert_allclose(feats[i], want[i], atol=1e-5)


# ---------------- guardrail ----------------

def test_warn_serve_pad_waste_fire_and_silent():
    from dinov3_tpu.configs.config import (
        serve_pad_waste_floor,
        warn_serve_pad_waste,
    )

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_serve_pad_waste(0.10) is None     # below threshold
    with pytest.warns(UserWarning, match=r"serve pad-waste axis \[mix-x\]"):
        msg = warn_serve_pad_waste(0.40, axis="mix-x")
    assert "40.0%" in msg and "serve.row_tokens" in msg

    # floor: row_tokens 40, patch 4, prefix 1: 16px images (17 tokens)
    # fit twice wasting 6/40; 12px (10 tokens) fit 4x wasting 0
    floor = serve_pad_waste_floor(40, 4, 1, 8, 16)
    assert floor["px"] == 16 and floor["seq_len"] == 17
    assert floor["waste"] == pytest.approx(6 / 40)
    assert 0.0 < floor["mean_waste"] < floor["waste"]


def test_packed_engine_build_warns_on_wasteful_envelope(tiny_serve):
    cfg, model, params, _ = tiny_serve
    # 8px-only traffic (5 tokens) in an 8-token row: 37.5% of every
    # row is structurally padding
    bad = _layout(rows=1, row_tokens=8, n_prefix=1, max_segments_per_row=2,
                  patch_size=4, min_px=8, max_px=8)
    with pytest.warns(UserWarning, match="serve pad-waste axis"):
        PackedServeEngine(model, params, bad, warn=True)


# ---------------- committed artifact ----------------

def test_serve_r14_acceptance():
    """The committed SERVE_r14.json (vit_small, CPU): packed >= 2x the
    rectangular oracle's sustained img/s on the mixed ragged mix at
    equal features, p50/p99 present for all three mixes, exactly one
    packed compile across the full replay, zero unattributed
    collectives in the packed program's census."""
    rec = json.loads(open(os.path.join(REPO, "SERVE_r14.json")).read())
    assert not rec["smoke"]
    assert rec["packed_compile_count"] == 1
    assert rec["packed_census"]["collective_unattributed"] == 0
    mixes = rec["mixes"]
    assert set(mixes) == {"uniform_224", "mixed_ragged", "heavy_tail"}
    for name, mix in mixes.items():
        for arm in ("packed", "oracle_rectangular", "oracle_per_image"):
            lat = mix[arm]["latency"]
            assert lat["p50_ms"] > 0 and lat["p99_ms"] >= lat["p50_ms"], (
                name, arm)
        assert mix["packed"]["compile_growth_during_measurement"] == 0
        assert mix["packed"]["serve"]["host_sync"]["fetches"] >= 1
    mr = mixes["mixed_ragged"]
    assert mr["speedup_vs_rectangular"] >= 2.0
    # equal features: bf16-compute reassociation tolerance on O(1)
    # layernormed outputs
    for arm in ("oracle_rectangular", "oracle_per_image"):
        agree = mr[f"features_vs_{arm}"]
        assert agree["cls_max_abs_diff"] <= 0.1
        assert agree["pooled_max_abs_diff"] <= 0.1
