"""A faithful PyTorch DINOv3 ViT oracle for golden-parity testing.

Implements Meta's released DINOv3 ViT semantics (pre-norm blocks, CLS +
storage tokens, axial RoPE on q/k patch tokens with prefix skipped,
LayerScale, exact-erf GELU, LN eps 1e-6) with the released checkpoints'
EXACT ``state_dict`` naming — the key set ``/root/reference/hubconf.py``
remaps (cls_token, storage_tokens, mask_token, patch_embed.proj.*,
rope_embed.periods, blocks.N.{norm1,attn.qkv,attn.proj,ls1,norm2,
mlp.fc1,mlp.fc2,ls2}.*, norm.*, plus the qkv ``bias_mask`` buffer).

Purpose: (a) its ``state_dict()`` is a structurally-true stand-in for the
released ``dinov3_vits16`` weights, so the torch->jax converter is tested
against the real layout offline; (b) its forward is an independent
implementation of the same math, so output parity actually validates the
JAX ViT/RoPE/head conventions (VERDICT r1 "what's missing" #2).

This module deliberately avoids looking anything up in dinov3_tpu — it is
written from the published DINOv3 architecture so that agreement is
evidence, not tautology.
"""

from __future__ import annotations

import math

import torch
import torch.nn as nn


class _Attention(nn.Module):
    def __init__(self, dim, num_heads):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = nn.Linear(dim, 3 * dim, bias=True)
        # released checkpoints carry a 0/1 mask buffer zeroing the k bias
        mask = torch.ones(3 * dim)
        mask[dim: 2 * dim] = 0.0
        self.qkv.register_buffer("bias_mask", mask)
        self.proj = nn.Linear(dim, dim, bias=True)

    def forward(self, x, sin, cos, n_prefix):
        B, N, D = x.shape
        h, d = self.num_heads, self.head_dim
        bias = self.qkv.bias * self.qkv.bias_mask
        qkv = torch.nn.functional.linear(x, self.qkv.weight, bias)
        q, k, v = qkv.split(D, dim=-1)
        q = q.reshape(B, N, h, d)
        k = k.reshape(B, N, h, d)
        v = v.reshape(B, N, h, d)

        def rope(t):
            patch = t[:, n_prefix:]
            x1, x2 = patch.chunk(2, dim=-1)
            rotated = torch.cat([-x2, x1], dim=-1)
            patch = patch * cos[None, :, None, :] + rotated * sin[None, :, None, :]
            return torch.cat([t[:, :n_prefix], patch], dim=1)

        q, k = rope(q), rope(k)
        logits = torch.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
        probs = torch.softmax(logits, dim=-1)
        out = torch.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, N, D)
        return self.proj(out)


class _LayerScale(nn.Module):
    def __init__(self, dim, init=1e-5):
        super().__init__()
        self.gamma = nn.Parameter(torch.full((dim,), init))

    def forward(self, x):
        return x * self.gamma


class _Mlp(nn.Module):
    def __init__(self, dim, hidden):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.fc2 = nn.Linear(hidden, dim)

    def forward(self, x):
        return self.fc2(torch.nn.functional.gelu(self.fc1(x)))


class _Block(nn.Module):
    def __init__(self, dim, num_heads, ffn_ratio=4.0, ls_init=1e-5):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.attn = _Attention(dim, num_heads)
        self.ls1 = _LayerScale(dim, ls_init)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = _Mlp(dim, int(dim * ffn_ratio))
        self.ls2 = _LayerScale(dim, ls_init)

    def forward(self, x, sin, cos, n_prefix):
        x = x + self.ls1(self.attn(self.norm1(x), sin, cos, n_prefix))
        x = x + self.ls2(self.mlp(self.norm2(x)))
        return x


class TorchDinoViT(nn.Module):
    """DINOv3 ViT with Meta's state_dict naming (see module docstring)."""

    def __init__(self, embed_dim=384, depth=12, num_heads=6, patch_size=16,
                 n_storage_tokens=4, ffn_ratio=4.0, rope_base=100.0,
                 ls_init=1e-5):
        super().__init__()
        self.patch_size = patch_size
        self.n_storage_tokens = n_storage_tokens
        d_head = embed_dim // num_heads
        self.cls_token = nn.Parameter(torch.zeros(1, 1, embed_dim))
        self.storage_tokens = nn.Parameter(
            torch.zeros(1, n_storage_tokens, embed_dim))
        self.mask_token = nn.Parameter(torch.zeros(1, embed_dim))

        class _PatchEmbed(nn.Module):
            def __init__(self):
                super().__init__()
                self.proj = nn.Conv2d(3, embed_dim, patch_size, patch_size)

        class _Rope(nn.Module):
            def __init__(self):
                super().__init__()
                n = d_head // 4
                periods = rope_base ** (
                    2.0 * torch.arange(n, dtype=torch.float32) / (d_head / 2.0)
                )
                self.register_buffer("periods", periods)

        self.patch_embed = _PatchEmbed()
        self.rope_embed = _Rope()
        self.blocks = nn.ModuleList(
            [_Block(embed_dim, num_heads, ffn_ratio, ls_init)
             for _ in range(depth)]
        )
        self.norm = nn.LayerNorm(embed_dim, eps=1e-6)

    def _rope_tables(self, Hp, Wp):
        # normalize_coords="separate": centers in [-1, 1] per axis
        ch = 2.0 * (torch.arange(Hp, dtype=torch.float32) + 0.5) / Hp - 1.0
        cw = 2.0 * (torch.arange(Wp, dtype=torch.float32) + 0.5) / Wp - 1.0
        gh, gw = torch.meshgrid(ch, cw, indexing="ij")
        coords = torch.stack([gh, gw], dim=-1).reshape(-1, 2)  # [HW, 2]
        angles = (2.0 * math.pi * coords[:, :, None]
                  / self.rope_embed.periods[None, None, :])
        angles = angles.reshape(angles.shape[0], -1)
        angles = torch.cat([angles, angles], dim=-1)  # [HW, d_head]
        return torch.sin(angles), torch.cos(angles)

    def forward(self, x):
        """x: [B, H, W, 3] float -> dict of features (NHWC input to match
        the JAX side's convention; converted to NCHW for the conv)."""
        B, H, W, _ = x.shape
        Hp, Wp = H // self.patch_size, W // self.patch_size
        t = self.patch_embed.proj(x.permute(0, 3, 1, 2))  # [B, D, Hp, Wp]
        t = t.flatten(2).transpose(1, 2)  # [B, HW, D], row-major
        tokens = torch.cat(
            [self.cls_token.expand(B, -1, -1),
             self.storage_tokens.expand(B, -1, -1), t], dim=1)
        sin, cos = self._rope_tables(Hp, Wp)
        n_prefix = 1 + self.n_storage_tokens
        for blk in self.blocks:
            tokens = blk(tokens, sin, cos, n_prefix)
        out = self.norm(tokens)
        return {
            "x_norm_clstoken": out[:, 0],
            "x_storage_tokens": out[:, 1: n_prefix],
            "x_norm_patchtokens": out[:, n_prefix:],
        }
