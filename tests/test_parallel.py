"""Mesh / GSPMD sharding tests on the 8-virtual-device CPU mesh.

Covers SURVEY.md §2.5's parallelism checklist the TPU-native way: params
born sharded over fsdp, batch over data axes, the full fused train step
executing under a multi-axis mesh with XLA-inserted collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.parallel import build_mesh
from dinov3_tpu.parallel.mesh import MeshSpec, data_parallel_size
from dinov3_tpu.train import build_train_setup, put_batch

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=32", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=32", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


def test_mesh_spec_resolution(eight_devices):
    assert MeshSpec(data=-1, fsdp=2).resolve(8) == (1, 4, 1, 2, 1, 1, 1)
    assert MeshSpec(data=2, fsdp=2, seq=2).resolve(8) == (1, 2, 1, 2, 2, 1, 1)
    assert MeshSpec(data=2, pipe=2, fsdp=2).resolve(8) == (1, 2, 2, 2, 1, 1, 1)
    assert MeshSpec(data=2, fsdp=2, expert=2).resolve(8) == (1, 2, 1, 2, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshSpec(data=3, fsdp=2).resolve(8)
    mesh = build_mesh(MeshSpec(data=-1, fsdp=2), devices=eight_devices)
    assert mesh.shape["data"] == 4 and mesh.shape["fsdp"] == 2
    assert data_parallel_size(mesh) == 8


@pytest.mark.parametrize("axes", [
    {"data": -1, "fsdp": 1},          # pure DP
    {"data": -1, "fsdp": 2},          # DP x FSDP (ZeRO)
    {"data": 2, "fsdp": 2, "tensor": 2},  # DP x FSDP x TP
])
def test_sharded_train_step(eight_devices, axes):
    extra = [f"parallel.{k}={v}" for k, v in axes.items()]
    cfg = smol_cfg(extra)
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)

    # params actually sharded over fsdp when fsdp > 1
    if axes.get("fsdp", 1) > 1:
        sharded = [
            s for s in jax.tree.leaves(setup.state_shardings.params)
            if "fsdp" in jax.tree.leaves(s.spec)
            or any("fsdp" in (ax if isinstance(ax, tuple) else (ax,))
                   for ax in s.spec if ax is not None)
        ]
        assert sharded, "no parameter got an fsdp-sharded spec"

    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))
    assert int(state.step) == 1
    # second step exercises the donated-buffer path
    state, metrics2 = setup.step_fn(
        state, dbatch, setup.scalars(1), jax.random.key(0)
    )
    assert np.isfinite(float(metrics2["total_loss"]))


def test_sharded_matches_single_device(eight_devices):
    """DPx(FSDP) global math == single-device math on the same batch."""
    B = 8
    cfg = smol_cfg(["parallel.data=-1", "parallel.fsdp=2",
                    "parallel.zero3=false"])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}

    setup8 = build_train_setup(cfg, batch, devices=eight_devices)
    cfg1 = smol_cfg(["parallel.data=1", "parallel.fsdp=1"])
    setup1 = build_train_setup(cfg1, batch, devices=eight_devices[:1])

    # identical init (same seed) -> identical first-step loss
    d8 = put_batch(batch, setup8.batch_shardings)
    d1 = put_batch(batch, setup1.batch_shardings)
    _, m8 = setup8.step_fn(setup8.state, d8, setup8.scalars(0),
                           jax.random.key(0))
    _, m1 = setup1.step_fn(setup1.state, d1, setup1.scalars(0),
                           jax.random.key(0))
    from conftest import legacy_tol

    # jaxlib < 0.5 XLA:CPU: measured 8.4e-4 cross-program skew
    # (documented in tests/conftest.py legacy_tol)
    np.testing.assert_allclose(
        float(m8["total_loss"]), float(m1["total_loss"]),
        rtol=legacy_tol(2e-4, 2.5e-3),
    )


def test_batch_sharding_divides_batch(eight_devices):
    from dinov3_tpu.parallel import batch_sharding

    mesh = build_mesh(MeshSpec(data=4, fsdp=2), devices=eight_devices)
    s = batch_sharding(mesh)
    x = jnp.zeros((16, 4, 4, 3))
    y = jax.device_put(x, s)
    shard_shapes = {tuple(sh.data.shape) for sh in y.addressable_shards}
    assert shard_shapes == {(2, 4, 4, 3)}


@pytest.mark.slow  # 61s: two 40-block compiles; tensor-axis collectives
# stay covered in the default set by test_sharded_train_step (DPxFSDPxTP)
def test_vocab_sharded_sinkhorn_7b_shapes(eight_devices):
    """7B-shape stress (VERDICT r2 #6): 40 scanned blocks at embed 64 with
    65536 prototypes sharded over the tensor axis. The Sinkhorn targets
    normalize over a vocab-sharded [B, K] logits array (XLA inserts the
    cross-tensor-axis reductions); the loss must match a replicated
    single-device run to fp32 tolerance."""
    proto = [
        "student.arch=vit_test40", "student.patch_size=4",
        "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
        "train.scan_layers=true",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=65536", "dino.head_hidden_dim=64",
        "dino.head_bottleneck_dim=32",
        "ibot.head_n_prototypes=65536", "ibot.head_hidden_dim=64",
        "ibot.head_bottleneck_dim=32",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
        "optim.warmup_epochs=1", "compute_precision.compute_dtype=fp32",
        "optim.scaling_rule=none",
    ]
    cfg8 = get_default_config()
    apply_dot_overrides(cfg8, proto + [
        "parallel.data=-1", "parallel.fsdp=2", "parallel.tensor=2",
        "parallel.zero3=false",
    ])
    B = 4
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg8, B, seed=0).items()}
    setup8 = build_train_setup(cfg8, batch, devices=eight_devices)
    assert setup8.mesh.shape["tensor"] == 2

    # the DINO-head prototype bank is actually vocab(tensor)-sharded
    dino_head = setup8.state_shardings.params["student"]["dino_head"]
    last = dino_head["prototypes"]
    assert any(
        "tensor" in (ax if isinstance(ax, tuple) else (ax,))
        for s in jax.tree.leaves(last) for ax in s.spec if ax is not None
    ), last

    cfg1 = get_default_config()
    apply_dot_overrides(cfg1, proto + ["parallel.data=1"])
    setup1 = build_train_setup(cfg1, batch, devices=eight_devices[:1])

    d8 = put_batch(batch, setup8.batch_shardings)
    d1 = put_batch(batch, setup1.batch_shardings)
    _, m8 = setup8.step_fn(setup8.state, d8, setup8.scalars(0),
                           jax.random.key(0))
    _, m1 = setup1.step_fn(setup1.state, d1, setup1.scalars(0),
                           jax.random.key(0))
    # the Sinkhorn-target-dependent losses are the subject: measured
    # rel diff ~1e-7 across the vocab-sharded vs replicated runs
    for key in ("dino_global_crops_loss", "dino_local_crops_loss",
                "ibot_loss"):
        np.testing.assert_allclose(
            float(m8[key]), float(m1[key]), rtol=2e-4, err_msg=key
        )
    # koleo picks top-k nearest neighbors among near-identical init
    # embeddings — reduction-order noise flips tie-breaks (measured
    # ~0.9% rel) — so the total gets a loose bound only
    np.testing.assert_allclose(
        float(m8["total_loss"]), float(m1["total_loss"]), rtol=2e-2,
        err_msg="total_loss",
    )


def test_sharded_train_step_subset_drop_path(eight_devices):
    """Reference-style batch-subset drop path (gather -> branch -> scatter)
    must stay legal under a data-sharded GSPMD mesh: the per-block gather
    with traced indices partitions (or falls back to a collective), and
    the step still runs and learns finitely."""
    cfg = smol_cfg([
        "parallel.data=-1", "parallel.fsdp=2", "parallel.zero3=false",
        "student.drop_path_rate=0.5", "student.drop_path_mode=subset",
    ])
    # data_parallel_size = data(4) x fsdp(2) = 8 -> groups=8; B=16 gives
    # Bg=2, keep_g=1 < Bg, so the subset gather/scatter path is actually
    # traced under the sharded mesh (B=8 would fall back to mask mode)
    B = 16
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))
    state, metrics2 = setup.step_fn(
        state, dbatch, setup.scalars(1), jax.random.key(0)
    )
    assert np.isfinite(float(metrics2["total_loss"]))


@pytest.mark.slow  # two full-step compiles on the 8-device mesh
def test_subset_drop_path_collective_budget(eight_devices):
    """The subset drop-path gather/scatter must not explode into per-block
    activation collectives under GSPMD. Measured on this mesh: the subset
    program emits FEWER all-gathers than the mask program (the branch
    runs on fewer rows) and its scatter-adds lower to all-reduces, with
    modest total growth. Pin those invariants loosely so a partitioner
    regression (e.g. a future scatter lowering that all-gathers the
    activation per block) fails loudly."""
    import re

    def counts(mode):
        cfg = smol_cfg([
            "parallel.data=-1", "parallel.fsdp=2", "parallel.zero3=false",
            "student.drop_path_rate=0.5",
            f"student.drop_path_mode={mode}",
        ])
        B = 16
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, B, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        dbatch = put_batch(batch, setup.batch_shardings)
        txt = setup.step_fn.lower(
            setup.state, dbatch, setup.scalars(0), jax.random.key(0)
        ).compile().as_text()
        return {
            op: len(re.findall(rf"\b{op}(?:-start)?\(", txt))
            for op in ("all-gather", "all-reduce", "reduce-scatter",
                       "all-to-all", "collective-permute")
        }

    mask, subset = counts("mask"), counts("subset")
    assert subset["all-gather"] <= mask["all-gather"], (mask, subset)
    assert sum(subset.values()) <= 1.5 * sum(mask.values()), (mask, subset)
