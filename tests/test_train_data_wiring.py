"""Trainer data plumbing: host sharding, stream resume, multires routing.

(VERDICT round 1 "what's weak" #2-#4: components existed but ``do_train``
never used them. These tests pin the wiring: ``build_data_iterator`` hands
each host a disjoint shard, resumes the stream at ``start_iter`` instead of
replaying batch 0, and routes crop-size-list recipes through the
multi-resolution combiner — reference intent at
dinov3_jax/data/samplers.py:49-60 and train/train.py:718-769,840.)
"""

import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.train.train import build_data_iterator

TINY = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "data.backend=synthetic",
]


def _cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, TINY + list(extra))
    return cfg


def _batches(it, n):
    return [next(it) for _ in range(n)]


def _same(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_synthetic_resume_continues_stream():
    cfg = _cfg()
    fresh = _batches(build_data_iterator(cfg, 4), 5)
    resumed = _batches(build_data_iterator(cfg, 4, start_iter=3), 2)
    _same(fresh[3], resumed[0])
    _same(fresh[4], resumed[1])


def test_synthetic_hosts_get_disjoint_shards():
    cfg = _cfg()
    b0 = next(build_data_iterator(cfg, 4, rank=0, world_size=2))
    b1 = next(build_data_iterator(cfg, 4, rank=1, world_size=2))
    # local shard: half the global batch...
    assert b0["global_crops"].shape[0] == b1["global_crops"].shape[0] == 4
    # ...and a different half on each host
    assert not np.array_equal(b0["global_crops"], b1["global_crops"])


def test_multires_synthetic_routing_and_resume():
    cfg = _cfg([
        "crops.global_crops_size=[16,12]", "crops.local_crops_size=[8,8]",
        "crops.global_local_crop_pairs_ratios=[0.5,0.5]",
    ])
    fresh = _batches(build_data_iterator(cfg, 4), 8)
    sizes = {b["global_crops"].shape[1] for b in fresh}
    assert sizes == {16, 12}, "both resolutions must appear in the stream"
    resumed = _batches(build_data_iterator(cfg, 4, start_iter=5), 3)
    for want, got in zip(fresh[5:], resumed):
        _same(want, got)


def test_multires_folder_pipeline_resume(tmp_path):
    """Real (folder) pipeline: the combined multi-resolution stream resumes
    exactly — combiner choices and per-resolution samplers both advance."""
    from PIL import Image

    from dinov3_tpu.data.pipeline import make_multires_train_pipeline

    root = tmp_path / "imgs"
    (root / "cls").mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(8):
        arr = rng.integers(0, 255, (20, 20, 3), dtype=np.uint8)
        Image.fromarray(arr).save(root / "cls" / f"{i}.png")
    cfg = _cfg([
        "crops.global_crops_size=[16,12]", "crops.local_crops_size=[8,8]",
        "crops.global_local_crop_pairs_ratios=[0.7,0.3]",
        "data.backend=folder", f"data.root={root}",
        "train.num_workers=2", "train.dataset_path=Synthetic:split=TRAIN",
    ])
    fresh = _batches(make_multires_train_pipeline(cfg, 2), 6)
    resumed = _batches(
        make_multires_train_pipeline(cfg, 2, sampler_advance_batches=4), 2)
    for want, got in zip(fresh[4:], resumed):
        _same(want, got)


@pytest.mark.slow
def test_trainer_resume_continues_data_stream(tmp_path):
    """End-to-end: train 4 iters uninterrupted vs 2 iters + resume; the
    resumed run must see the same batches (identical per-step losses)."""
    import json

    from dinov3_tpu.train.train import main as train_main

    common = TINY + [
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=1",
        "optim.warmup_epochs=0", "checkpointing.period=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
    ]

    def losses(path):
        with open(path) as f:
            return {json.loads(l)["iteration"]: json.loads(l)["total_loss"]
                    for l in f if l.strip()}

    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    train_main(["--output-dir", str(a_dir), "--no-resume",
                "--record-losses", str(a_dir / "losses.jsonl")] + common)
    train_main(["--output-dir", str(b_dir), "--no-resume",
                "--max-iterations", "2"] + common)
    out = train_main(["--output-dir", str(b_dir),
                      "--record-losses", str(b_dir / "losses.jsonl")] + common)
    assert out["iterations"] == 4
    la, lb = losses(a_dir / "losses.jsonl"), losses(b_dir / "losses.jsonl")
    assert set(lb) == {2, 3}, "resume must start at iteration 2"
    for it in (2, 3):
        assert la[it] == pytest.approx(lb[it], rel=1e-5), (
            f"iteration {it}: uninterrupted {la[it]} != resumed {lb[it]} "
            "(data stream replayed from 0?)"
        )


@pytest.mark.slow
def test_trainer_multires_recipe_reaches_step_fn(tmp_path):
    """A crop-size-list recipe (the vit7b16_high_res_adapt.yaml shape,
    scaled to vit_test) trains end-to-end on the synthetic backend, one jit
    cache entry per resolution."""
    from dinov3_tpu.train.train import main as train_main

    out = train_main([
        "--output-dir", str(tmp_path / "mr"), "--no-resume",
    ] + TINY + [
        "crops.global_crops_size=[16,12]", "crops.local_crops_size=[8,8]",
        "crops.global_local_crop_pairs_ratios=[0.5,0.5]",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=1",
        "optim.warmup_epochs=0",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
    ])
    assert out["iterations"] == 4
    assert np.isfinite(out["final_loss"])
